#!/usr/bin/env bash
# Tier-1 gate (ROADMAP.md "Tier-1 verify") + static analysis + a fast chaos
# smoke + seeded ingest-fuzz smokes (plain and sanitized).
#
# Usage: scripts/tier1.sh [--no-chaos]
#
# Stage 0 is static analysis: graftlint (tools/graftlint — repo-native AST
# rules: jit hygiene, exception-guard safety, chaos-site and config-field
# cross-checks), graftcheck (semantic graph contracts), graftrace
# (tools/graftrace — whole-program Eraser-style lockset race/deadlock
# analysis over every thread root, verdict recorded in the run-history
# ledger; its dynamic twin is TCR_LOCKCHECK=1, exercised by the chaos
# e2e) and ruff (curated pyflakes/bare-except set in
# pyproject.toml; skipped with a notice when the container doesn't ship
# ruff). Stage 1 is the exact ROADMAP tier-1 command: the full non-slow
# suite on the CPU backend (this already includes the non-slow chaos
# scenarios and the fuzz smokes). Stage 2 re-runs ONLY the fast chaos
# subset (-m 'chaos and not slow') so a robustness regression is named
# explicitly in CI output instead of drowning in the full run; pass
# --no-chaos to skip it. Then: a telemetry smoke (tiny run at
# telemetry=full — artifacts exist + validate, pipeline outputs
# byte-identical to telemetry=off), a live-observability smoke (tiny run
# with live_port armed — /healthz /metrics /progress served mid-run,
# SIGUSR1 flushes the flight recorder, outputs byte-identical to a
# live-off run), a graph-executor smoke (tiny workload
# under executor=graph vs imperative — counts CSV + consensus FASTA
# byte-identical, telemetry attributed per node), a sharded-mesh smoke
# (data=2 run byte-identical to unsharded; slice lost mid-polish ->
# degraded mesh -> still byte-identical; reshard hard gate), a perf-gate
# smoke (two
# tiny runs feed a shared run-history ledger; scripts/perf_gate.py stays
# quiet on an identical replay and exits nonzero on a seeded +30%
# regression; --report --critical-path explains the executed graph
# consistently with wall time), the differential ingest fuzzer
# standalone (5 seeds), a seeded-corpus replay through the ASan/UBSan
# parser build (scripts/fuzz_ingest.py --sanitized; the >=1000-corpus
# campaigns are the slow-marked tests), and a warm-serving daemon smoke
# (one warm daemon serves two HTTP-submitted jobs — the second with ZERO
# steady-state compiles and outputs byte-identical to the one-shot CLI —
# plus the slow-marked drain e2e: SIGTERM-equivalent stop mid-queue ->
# journal -> restarted daemon resumes both jobs to correct counts, plus
# the slice-pack arm: two stub tenants resident at once on disjoint
# slices with a device-lost isolation drill and a both-tenants drain
# journal), a serve-load smoke (scripts/serve_load.py seeded burst
# against an in-process stub daemon: exact per-reason rejection
# accounting, saturation 429s, a mid-drain 503, journal
# resume-to-completion, and a schema-valid load_report.json), and a
# packed serve-load smoke (--scenario packed: resident high-water >= 2
# on pairwise-disjoint slices under the same exact ledger).

set -o pipefail
cd "$(dirname "$0")/.."

echo "--- static analysis: graftlint (new findings only; known ones live"
echo "    in tools/graftlint/baseline.json with justifications) ---"
python -m tools.graftlint ont_tcrconsensus_tpu tests scripts tools \
    --baseline tools/graftlint/baseline.json
lrc=$?
if [ "$lrc" -ne 0 ]; then
    echo "graftlint FAILED (rc=$lrc)" >&2
    exit "$lrc"
fi

echo "--- static analysis: graftcheck (semantic graph-contract analyzer;"
echo "    jax-free — the run itself proves the production GraphSpec builds"
echo "    and analyzes without jax; --expect pins the known host"
echo "    round-trips so a new one fails CI) ---"
python -m tools.graftcheck --expect
gcrc=$?
if [ "$gcrc" -ne 0 ]; then
    echo "graftcheck FAILED (rc=$gcrc)" >&2
    exit "$gcrc"
fi
# exit-code/JSON parity: the --json body must carry the same exit_code the
# human run returned, so machine consumers never disagree with CI
gcjson=$(python -m tools.graftcheck --expect --json)
jrc=$?
jbody_rc=$(printf '%s' "$gcjson" | python -c \
    'import json,sys; print(json.load(sys.stdin)["exit_code"])')
if [ "$jrc" -ne "$gcrc" ] || [ "$jbody_rc" != "$gcrc" ]; then
    echo "graftcheck --json parity FAILED (human rc=$gcrc, json rc=$jrc," \
         "body exit_code=$jbody_rc)" >&2
    exit 1
fi

echo "--- static analysis: graftrace (whole-program lockset race/deadlock"
echo "    analyzer over the thread roots; jax-free; --expect pins the"
echo "    justified signal-path findings so a new race, order inversion,"
echo "    blocking-under-lock or signal-unsafe call fails CI) ---"
python -m tools.graftrace --expect
trrc=$?
if [ "$trrc" -ne 0 ]; then
    echo "graftrace FAILED (rc=$trrc)" >&2
    exit "$trrc"
fi
# same exit-code/JSON parity contract as graftcheck
trjson=$(python -m tools.graftrace --expect --json)
tjrc=$?
tjbody_rc=$(printf '%s' "$trjson" | python -c \
    'import json,sys; print(json.load(sys.stdin)["exit_code"])')
if [ "$tjrc" -ne "$trrc" ] || [ "$tjbody_rc" != "$trrc" ]; then
    echo "graftrace --json parity FAILED (human rc=$trrc, json rc=$tjrc," \
         "body exit_code=$tjbody_rc)" >&2
    exit 1
fi
# record the verdict in the run-history ledger (source=graftrace entries
# carry no perf fingerprint, so they never pollute perf-gate baselines)
mkdir -p .scratch
GRAFTRACE_JSON="$trjson" env JAX_PLATFORMS=cpu python - <<'EOF'
import json, os
body = json.loads(os.environ["GRAFTRACE_JSON"])
from ont_tcrconsensus_tpu.obs import history
entry = history.build_entry("graftrace", sha=history.git_sha(), extra={
    "graftrace": {
        "new_findings": body["count"],
        "baselined": len(body["baselined"]),
        "stale_expected": len(body["stale_expected"]),
        "roots": len(body["roots"]),
        "exit_code": body["exit_code"],
    },
})
history.append_entry(".scratch/history.jsonl", entry)
EOF
hrc=$?
if [ "$hrc" -ne 0 ]; then
    echo "graftrace ledger record FAILED (rc=$hrc)" >&2
    exit "$hrc"
fi

if command -v ruff >/dev/null 2>&1; then
    echo "--- static analysis: ruff ---"
    ruff check ont_tcrconsensus_tpu tests scripts tools
    rrc=$?
    if [ "$rrc" -ne 0 ]; then
        echo "ruff FAILED (rc=$rrc)" >&2
        exit "$rrc"
    fi
else
    echo "--- static analysis: ruff not installed; skipping (graftlint's" \
         "unused-import/bare-except rules cover the overlap) ---"
fi

rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly \
    2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)"
if [ "$rc" -ne 0 ]; then
    echo "tier-1 FAILED (rc=$rc)" >&2
    exit "$rc"
fi

if [ "${1:-}" != "--no-chaos" ]; then
    echo "--- chaos smoke (fault-injection e2e, non-slow subset) ---"
    timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
        -m 'chaos and not slow' -p no:cacheprovider -p no:xdist -p no:randomly
    crc=$?
    if [ "$crc" -ne 0 ]; then
        echo "chaos smoke FAILED (rc=$crc)" >&2
        exit "$crc"
    fi

    echo "--- watchdog chaos smoke (stall -> detected -> retried -> byte-identical;"
    echo "    corrupt-artifact -> caught by verify_resume -> recomputed) ---"
    timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest tests/test_chaos.py -q \
        -k "stall or corrupt_artifact" -m 'chaos and not slow' \
        -p no:cacheprovider -p no:xdist -p no:randomly
    wrc=$?
    if [ "$wrc" -ne 0 ]; then
        echo "watchdog chaos smoke FAILED (rc=$wrc)" >&2
        exit "$wrc"
    fi
    # the full liveness/integrity matrix (C-level hang, v1-manifest
    # migration e2e) is slow-marked: pytest -m 'chaos' tests/test_chaos.py
fi

echo "--- telemetry smoke (tiny run at telemetry=full: telemetry.json +"
echo "    trace.json exist and validate, incl. the transfers section —"
echo "    per-edge ledger, donation verdicts, static HBM; counts/consensus"
echo "    byte-identical to a telemetry=off run) ---"
timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest tests/test_obs.py -q \
    -k "telemetry_full_e2e_artifacts or telemetry_off_is_byte_identical" \
    -p no:cacheprovider -p no:xdist -p no:randomly
trc=$?
if [ "$trc" -ne 0 ]; then
    echo "telemetry smoke FAILED (rc=$trc)" >&2
    exit "$trc"
fi

echo "--- live observability smoke (tiny run with live_port armed: /healthz"
echo "    /metrics /progress fetched MID-RUN and valid, SIGUSR1 flushes a"
echo "    schema-valid flight recorder, counts/consensus byte-identical to"
echo "    a live-off run) ---"
timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest tests/test_live.py -q \
    -k "live_e2e" -p no:cacheprovider -p no:xdist -p no:randomly
vrc=$?
if [ "$vrc" -ne 0 ]; then
    echo "live observability smoke FAILED (rc=$vrc)" >&2
    exit "$vrc"
fi

echo "--- graph executor smoke (tiny workload under executor=graph vs"
echo "    imperative: counts CSV + consensus FASTA byte-identical, telemetry"
echo "    attributed per node) ---"
timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest tests/test_graph.py -q \
    -k "graph_vs_imperative_byte_identity or attributes_telemetry_per_node" \
    -p no:cacheprovider -p no:xdist -p no:randomly
grc=$?
if [ "$grc" -ne 0 ]; then
    echo "graph executor smoke FAILED (rc=$grc)" >&2
    exit "$grc"
fi

echo "--- sharded-mesh smoke (data=2 run byte-identical to the unsharded"
echo "    baseline; a slice lost mid-polish degrades the mesh and still"
echo "    completes byte-identically with a recorded mesh.degraded event;"
echo "    the executor refuses a graph whose declared shardings reshard) ---"
timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_pipeline_e2e.py tests/test_chaos.py tests/test_graph.py -q \
    -m "" \
    -k "counts_match_ground_truth or mesh_data2_byte_identical or mesh_device_lost or mesh_refuses_resharding" \
    -p no:cacheprovider -p no:xdist -p no:randomly
mrc=$?
if [ "$mrc" -ne 0 ]; then
    echo "sharded-mesh smoke FAILED (rc=$mrc)" >&2
    exit "$mrc"
fi

echo "--- perf-gate smoke (two tiny runs feed a shared history ledger:"
echo "    scripts/perf_gate.py passes on an identical replay and fails on"
echo "    a seeded +30% regression; a seeded host round-trip fails the"
echo "    bytes gate under the near-zero --rt-budget with measured-vs-"
echo "    allowed bytes; --report --critical-path explains the executed"
echo "    graph) ---"
timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_history.py tests/test_transfers.py -q \
    -k "perf_gate_passes_replay or perf_gate_cli or critical_path_matches or rt_budget" \
    -p no:cacheprovider -p no:xdist -p no:randomly
prc=$?
if [ "$prc" -ne 0 ]; then
    echo "perf-gate smoke FAILED (rc=$prc)" >&2
    exit "$prc"
fi

echo "--- ingest fuzz smoke (native vs Python differential, 5 seeds) ---"
timeout -k 10 300 python scripts/fuzz_ingest.py --seeds 5 --cases 20
frc=$?
if [ "$frc" -ne 0 ]; then
    echo "ingest fuzz smoke FAILED (rc=$frc)" >&2
    exit "$frc"
fi

echo "--- sanitized fuzz smoke (ASan/UBSan parser, 3 seeds) ---"
timeout -k 10 300 python scripts/fuzz_ingest.py --sanitized --seeds 3 --cases 20
src=$?
if [ "$src" -ne 0 ]; then
    echo "sanitized fuzz smoke FAILED (rc=$src)" >&2
    exit "$src"
fi
echo "--- warm-serving daemon smoke (warm daemon: job 2 dispatches with 0"
echo "    XLA compiles + byte-identical artifacts; drain journals the queue"
echo "    and a restarted daemon resumes it; slice-pack arm: two stub"
echo "    tenants resident AT ONCE on disjoint slices, device_lost on A's"
echo "    slice quarantines it and never perturbs B, drain journals every"
echo "    resident) ---"
# -m 'slow or not slow' overrides the default '-m not slow' addopts so the
# slow-marked drain/restart e2e runs here by name; the heavy packed e2es
# (test_packed_e2e_*) are slow-marked and deliberately NOT matched by -k
timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_serve.py tests/test_serve_slices.py -q \
    -k "serve_e2e or drain_journals or slice_pack" -m 'slow or not slow' \
    -p no:cacheprovider -p no:xdist -p no:randomly
drc=$?
if [ "$drc" -ne 0 ]; then
    echo "daemon smoke FAILED (rc=$drc)" >&2
    exit "$drc"
fi

echo "--- serve load smoke (scripts/serve_load.py: seeded burst against an"
echo "    in-process stub daemon — every 429/413/400/503 accounted exactly,"
echo "    queue saturation refused with exact queue_full counts, mid-drain"
echo "    submission 503s, journal -> restarted daemon completes every"
echo "    accepted job, load_report.json schema-valid) ---"
load_tmp=$(mktemp -d)
timeout -k 10 300 env JAX_PLATFORMS=cpu python scripts/serve_load.py \
    --scenario smoke --runner stub --seed 7 --period-s 0.4 \
    --stub-job-s 0.02 --queue-max 2 --burst 4 \
    --workdir "$load_tmp/state" --out "$load_tmp/load_report.json"
lsrc=$?
if [ "$lsrc" -ne 0 ]; then
    echo "serve load smoke FAILED (rc=$lsrc)" >&2
    rm -rf "$load_tmp"
    exit "$lsrc"
fi
python - "$load_tmp/load_report.json" <<'EOF'
import json, sys
sys.path.insert(0, "scripts")
import serve_load
report = json.load(open(sys.argv[1]))
assert serve_load.validate_report(report) == [], "load report schema"
assert report["invariants"] == [], report["invariants"]
sat = report["drills"]["saturation"]
assert sat["queue_full_429"] == sat["expected_429"] >= 1, sat
assert report["drills"]["mid_drain_503"] == 1, "mid-drain submit not 503"
resume = report["drills"]["resume"]
assert resume["journal_consumed"], "journal not consumed on restart"
assert resume["completed_after_restart"] == report["drills"]["drain"][
    "journaled"], "journaled jobs did not all complete after restart"
EOF
lvrc=$?
rm -rf "$load_tmp"
if [ "$lvrc" -ne 0 ]; then
    echo "serve load report verification FAILED (rc=$lvrc)" >&2
    exit "$lvrc"
fi

echo "--- packed serve load smoke (scripts/serve_load.py --scenario packed:"
echo "    a 2-wide runner pool packs stub tenants onto disjoint device"
echo "    slices; the report proves resident high-water >= 2, pairwise-"
echo "    disjoint leases, live tenant labels on /metrics, and the same"
echo "    exact submitted == accepted + rejected accounting) ---"
pack_tmp=$(mktemp -d)
timeout -k 10 300 env JAX_PLATFORMS=cpu python scripts/serve_load.py \
    --scenario packed --workers 2 --seed 11 --mix "ok=3,over_budget=1" \
    --period-s 0.2 --stub-job-s 0.02 --queue-max 4 \
    --workdir "$pack_tmp/state" --out "$pack_tmp/load_report.json"
psrc=$?
if [ "$psrc" -ne 0 ]; then
    echo "packed serve load smoke FAILED (rc=$psrc)" >&2
    rm -rf "$pack_tmp"
    exit "$psrc"
fi
python - "$pack_tmp/load_report.json" <<'EOF'
import json, sys
sys.path.insert(0, "scripts")
import serve_load
report = json.load(open(sys.argv[1]))
assert serve_load.validate_report(report) == [], "packed report schema"
assert report["invariants"] == [], report["invariants"]
packed = report["drills"]["packed"]
assert packed["resident_high_water"] >= 2, packed
assert packed["disjoint_slices"] is True, packed
rej = sum(report["rejected_by_reason"].values())
assert report["submitted"] == report["accepted"] + rej, report
assert report["drills"]["metrics"]["slice_busy_tenant_labels"] >= 2, \
    report["drills"]["metrics"]
EOF
pvrc=$?
rm -rf "$pack_tmp"
if [ "$pvrc" -ne 0 ]; then
    echo "packed serve load verification FAILED (rc=$pvrc)" >&2
    exit "$pvrc"
fi
echo "tier-1 OK"
