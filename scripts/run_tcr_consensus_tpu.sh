#!/usr/bin/env bash
# TPU-VM launcher for the consensus pipeline — the deployment analogue of the
# reference's SLURM wrapper (/root/reference/scripts/run_tcr_consensus_slurm.sh,
# which sbatches 128 CPUs / 275 GB for tcr_consensus <run_config.json>).
#
# Single host (one TPU VM, 1-8 chips):
#   ./run_tcr_consensus_tpu.sh run_config.json
#
# Multi-host TPU pod slice (e.g. v5e-16 = 2 hosts x 8 chips): run this script
# on every host via gcloud's --worker=all fan-out; jax.distributed picks up
# the pod topology from the TPU metadata and the pipeline shards its device
# batches over the global mesh (shard-by-barcode across hosts is the
# recommended mesh_shape, SURVEY §2.3):
#   gcloud compute tpus tpu-vm ssh "$TPU_NAME" --zone="$ZONE" --worker=all \
#     --command="cd $REPO_DIR && ./scripts/run_tcr_consensus_tpu.sh run_config.json"
set -euo pipefail

CONFIG="${1:?usage: run_tcr_consensus_tpu.sh <run_config.json>}"

# multi-host: initialize jax.distributed before the pipeline builds its mesh
# (no-op on a single host; TPU_WORKER_HOSTNAMES is set by the TPU runtime)
export TCR_CONSENSUS_DISTRIBUTED="${TPU_WORKER_HOSTNAMES:+1}"

LOG_DIR="$(dirname "$CONFIG")/logs"
mkdir -p "$LOG_DIR"
STAMP="$(date +%Y%m%d_%H%M%S)"

exec tcr-consensus-tpu "$CONFIG" \
  > "$LOG_DIR/tcr_consensus_tpu_${STAMP}.log" \
  2> "$LOG_DIR/tcr_consensus_tpu_${STAMP}.err"
