#!/usr/bin/env python
"""Seeded, deterministic load generator for the warm-serving daemon.

Hammers ``POST /jobs`` with a configurable tenant mix and emits a
machine-readable ``load_report.json`` whose rejection ledger is EXACT:

    submitted == accepted + sum(rejected_by_reason)
    accepted  == completed + poisoned + failed + journaled_remaining

Four scenarios, all seeded (same ``--seed`` + ``--mix`` => the same
submission kinds at the same offsets):

- ``smoke``     — in-process daemon, stub runner by default: a seeded
  mix burst (202/400/409/413 accounting), a gated saturation burst with
  EXACT queue_full 429 counts, one mid-drain 503, drain with jobs still
  queued -> journal -> restarted daemon resumes -> every accepted job
  completes. Seconds-fast; the tier-1 load-smoke stage runs this.
- ``packed``    — in-process daemon, stub runner, ``--workers`` slice-
  packed runner pool: a gate holds every stub job mid-run until >= 2
  tenants are provably resident AT ONCE on pairwise-disjoint device
  slices (concurrency high-water + disjointness land in the report as
  invariants), then completes everything under the same exact ledger.
  The tier-1 slice-pack smoke runs this.
- ``sustained`` — in-process daemon, real pipeline: N tenants served
  back-to-back through one warm process; p50/p99 job wait,
  dispatch-to-first-stage latency, reads/s over the busy window,
  steady-state compile count from the LAST tenant's telemetry.json, and
  measured cold-start seconds. ``--ledger`` appends the
  ``source:"serve_load"`` entry `evaluate_load_gate` regresses against.
- ``drain``     — subprocess daemon, SIGTERM under load: mid-drain
  submissions 503, exit 143, journal carries the queue, a restarted
  daemon completes everything with counts CSV + consensus FASTA
  byte-identical to an uninterrupted run.
- ``crash``     — subprocess daemon with a ``TCR_CHAOS`` plan that
  raises in the serve loop itself: flight recorder flushed under
  ``serve_crash:<Type>``, every accepted job journaled, a clean restart
  completes them byte-identically.

The stub runner (smoke default) replaces ``run_with_config`` with a
short sleep: it exercises the CONTROL plane (admission, queue, journal,
metrics, drain) without pipeline work — the real-runner scenarios and
the slow e2e tests cover the data plane. Exit code is nonzero whenever
an invariant, drill verification, or report-schema check fails.

Usage:
    python scripts/serve_load.py --scenario smoke --out load_report.json
    python scripts/serve_load.py --scenario sustained --tenants 4 \
        --reads-per-molecule 12 --ledger BENCH_HISTORY.jsonl --cpu
"""

from __future__ import annotations

import argparse
import json
import math
import os
import random
import shutil
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPORT_SCHEMA = 1

#: submission kinds a schedule can carry and the refusal each provokes
MIX_KINDS = ("ok", "over_budget", "invalid_config", "oversized_body")

#: fallback HTTP-status -> reason mapping for rejection bodies without a
#: machine-readable ``error`` field (413 fires in the live plane)
STATUS_REASONS = {
    429: "queue_full", 409: "over_budget", 400: "invalid_config",
    413: "body_too_large", 503: "draining",
}

TERMINAL_STATES = ("done", "failed", "poisoned")


# --- deterministic schedule ---------------------------------------------------


def parse_mix(spec: str) -> dict[str, int]:
    """``"ok=6,over_budget=2"`` -> ``{"ok": 6, "over_budget": 2}``."""
    out: dict[str, int] = {}
    for part in filter(None, (p.strip() for p in spec.split(","))):
        kind, _, n = part.partition("=")
        if kind not in MIX_KINDS:
            raise ValueError(f"unknown mix kind {kind!r} (known: {MIX_KINDS})")
        count = int(n)
        if count < 0:
            raise ValueError(f"negative count for mix kind {kind!r}")
        out[kind] = out.get(kind, 0) + count
    if sum(out.values()) <= 0:
        raise ValueError(f"mix {spec!r} schedules no submissions")
    return out


def build_schedule(seed: int, mix: dict[str, int],
                   period_s: float) -> list[dict]:
    """Open-loop schedule: kinds are a seeded shuffle of the mix
    multiset, offsets a seeded sorted uniform draw over [0, period_s).
    Pure function of (seed, mix, period_s) — replayable by construction."""
    rng = random.Random(seed)
    kinds = [k for k, n in sorted(mix.items()) for _ in range(n)]
    rng.shuffle(kinds)
    offsets = sorted(rng.uniform(0.0, period_s) for _ in kinds)
    return [{"i": i, "t": round(t, 4), "kind": kind}
            for i, (t, kind) in enumerate(zip(offsets, kinds))]


def payload_for(kind: str, base: dict) -> tuple[dict | None, bytes | None]:
    """(json_object, raw_bytes) for one submission kind."""
    if kind == "ok":
        return dict(base), None
    if kind == "over_budget":
        return {**base, "read_batch_size": 1 << 24}, None
    if kind == "invalid_config":
        return {**base, "no_such_knob_from_serve_load": 1}, None
    if kind == "oversized_body":
        return None, b'{"pad": "' + b"x" * (1 << 20) + b'"}'
    raise ValueError(f"unknown kind {kind!r}")


# --- HTTP ---------------------------------------------------------------------


def _get(url: str, timeout: float = 30.0) -> tuple[int, dict]:
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read().decode() or "null")
    except urllib.error.HTTPError as err:
        body = err.read().decode()
        return err.code, (json.loads(body) if body.startswith("{") else {})


def _post(url: str, obj=None, data: bytes | None = None,
          timeout: float = 30.0) -> tuple[int, dict]:
    payload = json.dumps(obj).encode() if data is None else data
    req = urllib.request.Request(
        url, data=payload, method="POST",
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read().decode() or "null")
    except urllib.error.HTTPError as err:
        body = err.read().decode()
        return err.code, (json.loads(body) if body.startswith("{") else {})


# --- the rejection ledger -----------------------------------------------------


class Ledger:
    """Every submission's outcome, counted the moment the response lands
    — the accounting invariants are checked against THIS, not against
    daemon-side telemetry, so a dropped response is a visible hole."""

    def __init__(self) -> None:
        self.submitted = 0
        self.accepted = 0
        self.rejected_by_reason: dict[str, int] = {}
        self.accepted_ids: list[str] = []
        self.records: list[dict] = []

    def record(self, spec_kind: str, status: int, body: dict) -> None:
        self.submitted += 1
        if status == 202:
            self.accepted += 1
            self.accepted_ids.append(body["id"])
        else:
            reason = (body.get("error") if isinstance(body, dict) else None) \
                or STATUS_REASONS.get(status, f"http_{status}")
            self.rejected_by_reason[reason] = (
                self.rejected_by_reason.get(reason, 0) + 1)
        self.records.append(
            {"kind": spec_kind, "status": status,
             "id": body.get("id") if isinstance(body, dict) else None})


def run_schedule(jobs_url: str, schedule: list[dict], base: dict,
                 ledger: Ledger) -> None:
    """Submit the schedule open-loop: each POST fires at its offset
    regardless of earlier responses (the generator never self-throttles
    — that is the point of a saturation drill)."""
    t0 = time.monotonic()
    for spec in schedule:
        delay = spec["t"] - (time.monotonic() - t0)
        if delay > 0:
            time.sleep(delay)
        obj, data = payload_for(spec["kind"], base)
        status, body = _post(jobs_url, obj, data)
        ledger.record(spec["kind"], status, body)


def wait_terminal(jobs_url: str, job_ids: list[str],
                  timeout_s: float, poll_s: float = 0.1) -> dict[str, dict]:
    """Job id -> terminal snapshot; raises on timeout (a wedged loop is
    exactly what this harness exists to catch)."""
    states: dict[str, dict] = {}
    deadline = time.monotonic() + timeout_s
    while len(states) < len(job_ids):
        if time.monotonic() > deadline:
            missing = [j for j in job_ids if j not in states]
            raise RuntimeError(
                f"{len(missing)} job(s) not terminal after {timeout_s}s: "
                f"{missing[:8]}")
        for jid in job_ids:
            if jid in states:
                continue
            st, cur = _get(f"{jobs_url}/{jid}")
            if st == 200 and cur.get("state") in TERMINAL_STATES:
                states[jid] = cur
        time.sleep(poll_s)
    return states


# --- report -------------------------------------------------------------------


def percentile(values: list[float], p: float) -> float | None:
    """Nearest-rank percentile (exact for the small-N SLO tables)."""
    if not values:
        return None
    s = sorted(values)
    k = max(1, math.ceil(p / 100.0 * len(s)))
    return s[k - 1]


def summarize_waits(snaps: list[dict]) -> dict:
    waits = [s["wait_s"] for s in snaps if s.get("wait_s") is not None]
    stages = [s["first_stage_s"] for s in snaps
              if s.get("first_stage_s") is not None]
    rnd = lambda v: round(v, 4) if v is not None else None  # noqa: E731
    return {
        "wait_s": {"p50": rnd(percentile(waits, 50)),
                   "p99": rnd(percentile(waits, 99))},
        "first_stage_s": {"p50": rnd(percentile(stages, 50)),
                          "p99": rnd(percentile(stages, 99))},
    }


def check_invariants(report: dict) -> list[str]:
    """The exact-accounting contract; every violation is a returned
    string (empty == sound)."""
    problems = []
    rej = sum(report.get("rejected_by_reason", {}).values())
    if report["submitted"] != report["accepted"] + rej:
        problems.append(
            f"submitted ({report['submitted']}) != accepted "
            f"({report['accepted']}) + rejected ({rej})")
    terminal = (report["completed"] + report["poisoned"] + report["failed"]
                + report.get("journaled_remaining", 0))
    if report["accepted"] != terminal:
        problems.append(
            f"accepted ({report['accepted']}) != completed "
            f"({report['completed']}) + poisoned ({report['poisoned']}) + "
            f"failed ({report['failed']}) + journaled_remaining "
            f"({report.get('journaled_remaining', 0)})")
    return problems


_REQUIRED = {
    "schema": int, "source": str, "scenario": str, "seed": int,
    "submitted": int, "accepted": int, "completed": int, "poisoned": int,
    "failed": int, "rejected_by_reason": dict, "wait_s": dict,
    "first_stage_s": dict, "invariants": list,
}


def validate_report(report: dict) -> list[str]:
    """Schema problems (empty == valid); additive keys are fine."""
    problems = []
    for key, typ in _REQUIRED.items():
        if key not in report:
            problems.append(f"missing required key {key!r}")
        elif not isinstance(report[key], typ):
            problems.append(
                f"key {key!r} is {type(report[key]).__name__}, "
                f"want {typ.__name__}")
    if report.get("source") != "serve_load":
        problems.append('source must be "serve_load"')
    for sub in ("wait_s", "first_stage_s"):
        d = report.get(sub)
        if isinstance(d, dict):
            for pk in ("p50", "p99"):
                if pk not in d:
                    problems.append(f"{sub} missing {pk!r}")
    return problems


def base_report(args, scenario: str) -> dict:
    return {
        "schema": REPORT_SCHEMA,
        "source": "serve_load",
        "scenario": scenario,
        "seed": args.seed,
        "t_wall": round(time.time(), 3),
        "submitted": 0, "accepted": 0, "completed": 0,
        "poisoned": 0, "failed": 0, "journaled_remaining": 0,
        "rejected_by_reason": {},
        "wait_s": {"p50": None, "p99": None},
        "first_stage_s": {"p50": None, "p99": None},
        "reads_per_sec": None, "n_reads": None,
        "steady_compile_count": None, "cold_start_s": None,
        "drills": {}, "invariants": [],
    }


# --- in-process daemon plumbing ----------------------------------------------


def _start_daemon_thread(daemon):
    out = {"exit": None, "error": None}

    def _run():
        try:
            out["exit"] = daemon.serve_forever()
        except BaseException as exc:  # crash drills land here
            out["error"] = repr(exc)

    th = threading.Thread(target=_run, name="serve-load-daemon", daemon=True)
    th.start()
    return th, out


def _wait_live_server(timeout_s: float = 120.0):
    from ont_tcrconsensus_tpu.obs import live as obs_live

    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        srv = obs_live.server()
        if srv is not None:
            return srv
        time.sleep(0.05)
    raise RuntimeError("daemon never armed its live plane")


def _terminal_counts(snapshots: list[dict]) -> dict[str, int]:
    counts = {"done": 0, "failed": 0, "poisoned": 0}
    for snap in snapshots:
        if snap.get("state") in counts:
            counts[snap["state"]] += 1
    return counts


# --- scenario: smoke ----------------------------------------------------------


def scenario_smoke(args) -> dict:
    """Control-plane proof in seconds: mix accounting, exact saturation
    429s, one mid-drain 503, journal -> restart -> resume-to-done."""
    from ont_tcrconsensus_tpu.pipeline import run as run_mod
    from ont_tcrconsensus_tpu.serve import queue as queue_mod
    from ont_tcrconsensus_tpu.serve.daemon import Daemon

    report = base_report(args, "smoke")
    state_dir = os.path.join(args.workdir, "state")
    # fastq_pass_dir must be workdir-rooted even under the stub runner:
    # the daemon's success path appends a serve history entry beneath it
    template = {"reference_file": os.path.join(args.workdir, "r.fa"),
                "fastq_pass_dir": os.path.join(args.workdir, "fq")}
    gate = threading.Event()
    gate.set()

    def stub_run(cfg):
        gate.wait(timeout=60.0)
        time.sleep(args.stub_job_s)
        return {"barcode01": {"region0": 1}}

    real_run = run_mod.run_with_config
    if args.runner == "stub":
        run_mod.run_with_config = stub_run
    ledger = Ledger()
    try:
        daemon = Daemon(template, port=0, state_dir=state_dir,
                        queue_max=args.queue_max, do_prewarm=False)
        th, out = _start_daemon_thread(daemon)
        srv = _wait_live_server()
        jobs_url = f"http://127.0.0.1:{srv.port}/jobs"

        # phase A: the seeded mix — every refusal reason metered exactly
        schedule = build_schedule(args.seed, parse_mix(args.mix),
                                  args.period_s)
        run_schedule(jobs_url, schedule, template, ledger)
        wait_terminal(jobs_url, list(ledger.accepted_ids), args.timeout_s)

        # phase B: gated saturation — one job running (held on the gate),
        # queue filled to the brim, overflow gets EXACTLY counted 429s
        gate.clear()
        burst = args.burst or (args.queue_max + 2)
        st, body = _post(jobs_url, template)
        ledger.record("ok", st, body)
        deadline = time.monotonic() + 30.0
        while daemon.queue.depth() > 0 and time.monotonic() < deadline:
            time.sleep(0.01)  # the held job must be POPPED, not queued
        before_429 = ledger.rejected_by_reason.get("queue_full", 0)
        for _ in range(burst):
            st, body = _post(jobs_url, template)
            ledger.record("ok", st, body)
        exact_429 = (ledger.rejected_by_reason.get("queue_full", 0)
                     - before_429)
        report["drills"]["saturation"] = {
            "burst": burst, "queue_max": args.queue_max,
            "queue_full_429": exact_429,
            "expected_429": burst - args.queue_max,
        }

        # metrics satellite evidence: live depth gauge + per-reason family
        st, _ = _get(f"http://127.0.0.1:{srv.port}/healthz")
        metrics_txt = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics", timeout=30).read().decode()
        report["drills"]["metrics"] = {
            "serve_rejected_total": sum(
                1 for line in metrics_txt.splitlines()
                if line.startswith("tcr_serve_rejected_total{")),
            "live_queue_depth_gauge": any(
                line.startswith('tcr_gauge_current{site="serve.queue_depth"')
                for line in metrics_txt.splitlines()),
        }

        # phase C: drain under load — stop while the gate still holds the
        # running job and the queue is full; one more submit must 503
        daemon.request_stop()
        st, body = _post(jobs_url, template)
        ledger.record("ok", st, body)
        report["drills"]["mid_drain_503"] = int(st == 503)
        gate.set()
        th.join(timeout=120.0)
        if th.is_alive():
            raise RuntimeError("daemon did not drain after request_stop")
        gen1 = daemon.queue.snapshot()
        gen1_counts = _terminal_counts(gen1)
        journal_file = queue_mod.journal_path(state_dir)
        with open(journal_file) as fh:
            journaled = len(json.load(fh)["jobs"])
        report["drills"]["drain"] = {
            "exit_code": out["exit"], "error": out["error"],
            "journaled": journaled,
        }

        # phase D: restart — the journal resumes, everything completes
        daemon2 = Daemon(template, port=0, state_dir=state_dir,
                         queue_max=max(args.queue_max, journaled),
                         do_prewarm=False)
        th2, out2 = _start_daemon_thread(daemon2)
        srv2 = _wait_live_server()
        jobs_url2 = f"http://127.0.0.1:{srv2.port}/jobs"
        deadline = time.monotonic() + args.timeout_s
        listing: dict = {}
        while time.monotonic() < deadline:
            st, listing = _get(jobs_url2)
            if st == 200 and listing.get("jobs_done", 0) >= journaled:
                break
            time.sleep(0.05)
        daemon2.request_stop()
        th2.join(timeout=120.0)
        gen2 = daemon2.queue.snapshot()
        gen2_counts = _terminal_counts(gen2)
        report["drills"]["resume"] = {
            "resumed": len(gen2), "completed_after_restart":
            gen2_counts["done"], "journal_consumed":
            not os.path.exists(journal_file), "exit_code": out2["exit"],
        }

        report.update({
            "submitted": ledger.submitted,
            "accepted": ledger.accepted,
            "rejected_by_reason": dict(sorted(
                ledger.rejected_by_reason.items())),
            "completed": gen1_counts["done"] + gen2_counts["done"],
            "failed": gen1_counts["failed"] + gen2_counts["failed"],
            "poisoned": gen1_counts["poisoned"] + gen2_counts["poisoned"],
            "journaled_remaining": journaled - len(gen2),
            "runner": args.runner,
        })
        report.update(summarize_waits(gen1 + gen2))
        if exact_429 != burst - args.queue_max:
            report["invariants"].append(
                f"saturation burst of {burst} over queue_max="
                f"{args.queue_max} produced {exact_429} queue_full 429s, "
                f"want exactly {burst - args.queue_max}")
        if report["drills"]["mid_drain_503"] != 1:
            report["invariants"].append("mid-drain submission was not 503")
        if gen2_counts["done"] != journaled:
            report["invariants"].append(
                f"{journaled} journaled but only {gen2_counts['done']} "
                "completed after restart")
    finally:
        run_mod.run_with_config = real_run
    return report


# --- scenario: packed ---------------------------------------------------------


def scenario_packed(args) -> dict:
    """Slice-packed runner pool under load, stub runner: ``--workers``
    jobs resident AT ONCE on disjoint device slices. A gate holds every
    stub job mid-run until the concurrency high-water has provably
    reached the pool width, and the packed invariants ride the same
    exact ledger as every other scenario:

        submitted == accepted + sum(rejected_by_reason)
        resident high-water >= 2
        concurrent leases pairwise disjoint
    """
    from ont_tcrconsensus_tpu.pipeline import run as run_mod
    from ont_tcrconsensus_tpu.robustness import shutdown
    from ont_tcrconsensus_tpu.serve.daemon import Daemon

    report = base_report(args, "packed")
    state_dir = os.path.join(args.workdir, "state")
    template = {"reference_file": os.path.join(args.workdir, "r.fa"),
                "fastq_pass_dir": os.path.join(args.workdir, "fq")}
    gate = threading.Event()

    def stub_run(cfg):
        deadline = time.monotonic() + 60.0
        while not gate.is_set() and time.monotonic() < deadline:
            # the daemon's drain must be able to preempt a gated stub
            # exactly like a real run at a stage boundary
            shutdown.checkpoint("stub.run")
            time.sleep(0.01)
        time.sleep(args.stub_job_s)
        return {"barcode01": {"region0": 1}}

    real_run = run_mod.run_with_config
    run_mod.run_with_config = stub_run
    ledger = Ledger()
    high_water = 0
    disjoint_ok = True
    overlap_seen: list[str] = []
    try:
        daemon = Daemon(template, port=0, state_dir=state_dir,
                        queue_max=max(args.queue_max, args.tenants),
                        do_prewarm=False, workers=args.workers)
        if daemon.allocator is None:
            raise RuntimeError(
                f"packed scenario needs a runner pool (workers="
                f"{args.workers} gave no allocator)")
        th, out = _start_daemon_thread(daemon)
        srv = _wait_live_server()
        jobs_url = f"http://127.0.0.1:{srv.port}/jobs"

        # the seeded mix, same as smoke: refusals stay exactly metered
        # while the accepted jobs pile onto the pool behind the gate
        schedule = build_schedule(args.seed, parse_mix(args.mix),
                                  args.period_s)
        run_schedule(jobs_url, schedule, template, ledger)

        # hold the gate until the pool is provably packed: >= 2 tenants
        # resident at once on pairwise-disjoint slices
        deadline = time.monotonic() + args.timeout_s
        while time.monotonic() < deadline:
            snap = daemon.jobs_snapshot()
            leases = snap.get("slices", {}).get("leases", {})
            high_water = max(high_water, snap.get("resident_jobs", 0))
            claimed: set[str] = set()
            for job_id, lease in sorted(leases.items()):
                devs = set(lease["devices"])
                if claimed & devs:
                    disjoint_ok = False
                claimed |= devs
            if len(leases) >= 2 and not overlap_seen:
                overlap_seen = sorted(
                    f"{jid}@{lease['slice']}"
                    for jid, lease in leases.items())
            if high_water >= min(2, args.workers):
                break
            time.sleep(0.02)
        # scrape /metrics while the pool is still packed: the tenant
        # labels on tcr_mesh_slice_busy only exist while leases are live
        metrics_txt = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics", timeout=30).read().decode()
        report["drills"]["metrics"] = {
            "resident_jobs_gauge": any(
                line.startswith("tcr_serve_resident_jobs")
                for line in metrics_txt.splitlines()),
            "slice_busy_tenant_labels": sum(
                1 for line in metrics_txt.splitlines()
                if line.startswith("tcr_mesh_slice_busy{") and
                "tenant=" in line),
        }
        gate.set()
        snaps = wait_terminal(jobs_url, list(ledger.accepted_ids),
                              args.timeout_s)
        pool = daemon.allocator.snapshot()
        daemon.request_stop()
        th.join(timeout=120.0)
        if th.is_alive():
            raise RuntimeError("packed daemon did not drain")
        counts = _terminal_counts(list(snaps.values()))
        report["drills"]["packed"] = {
            "workers": args.workers,
            "resident_high_water": high_water,
            "disjoint_slices": disjoint_ok,
            "overlap_observed": overlap_seen,
            "quarantined": pool["quarantined"],
            "exit_code": out["exit"],
        }
        report.update({
            "submitted": ledger.submitted,
            "accepted": ledger.accepted,
            "rejected_by_reason": dict(sorted(
                ledger.rejected_by_reason.items())),
            "completed": counts["done"],
            "failed": counts["failed"],
            "poisoned": counts["poisoned"],
            "journaled_remaining": 0,
            "runner": "stub",
        })
        report.update(summarize_waits(list(snaps.values())))
        if high_water < min(2, args.workers):
            report["invariants"].append(
                f"resident high-water {high_water} never reached "
                f"{min(2, args.workers)} — the pool never packed")
        if not disjoint_ok:
            report["invariants"].append(
                "concurrent leases shared a device — slice isolation "
                "is broken")
        if not report["drills"]["metrics"]["resident_jobs_gauge"]:
            report["invariants"].append(
                "/metrics has no tcr_serve_resident_jobs gauge")
        if report["drills"]["metrics"]["slice_busy_tenant_labels"] < 2:
            report["invariants"].append(
                "/metrics showed fewer than 2 tenant-labelled "
                "tcr_mesh_slice_busy slices while the pool was packed")
        if args.ledger:
            from ont_tcrconsensus_tpu.obs import history as obs_history
            from ont_tcrconsensus_tpu.pipeline.config import RunConfig

            # stub runner: no reads/s — the entry still carries the
            # packed-residency evidence and the wait SLOs, and the load
            # gate accepts it (reads_per_sec simply isn't gated)
            cfg = RunConfig.from_dict(dict(template))
            entry = obs_history.build_entry(
                "serve_load",
                fingerprint=obs_history.config_fingerprint(cfg),
                sha=obs_history.git_sha(),
                backend=obs_history.detect_backend(),
                extra={
                    "scenario": "packed",
                    "p50_wait_s": report["wait_s"]["p50"],
                    "p99_wait_s": report["wait_s"]["p99"],
                    "workers": args.workers,
                    "resident_high_water": high_water,
                    "submitted": ledger.submitted,
                    "accepted": ledger.accepted,
                    "completed": counts["done"],
                    "poisoned": counts["poisoned"],
                    "rejected_by_reason": dict(ledger.rejected_by_reason),
                },
            )
            obs_history.append_entry(args.ledger, entry)
            report["drills"]["ledger_entry"] = {
                "path": args.ledger, "fingerprint": entry["fingerprint"]}
    finally:
        run_mod.run_with_config = real_run
    return report


# --- scenario: sustained ------------------------------------------------------


def _build_library(args):
    from ont_tcrconsensus_tpu.io import fastx, simulator

    lib = simulator.simulate_library(
        seed=args.seed + 29,
        num_regions=args.regions,
        molecules_per_region=(args.molecules, args.molecules + 1),
        reads_per_molecule=(args.reads_per_molecule,
                            args.reads_per_molecule + 2),
        sub_rate=0.006, ins_rate=0.003, del_rate=0.003,
        region_len=(700, 850),
    )
    src = os.path.join(args.workdir, "dataset")
    os.makedirs(src, exist_ok=True)
    fastx.write_fasta(os.path.join(src, "reference.fa"),
                      lib.reference.items())
    fq_dir = os.path.join(src, "fastq_pass", "barcode01")
    os.makedirs(fq_dir, exist_ok=True)
    fastx.write_fastq(os.path.join(fq_dir, "barcode01.fastq.gz"), lib.reads)
    return src, lib


def _stage_tenant(src: str, root: str) -> dict:
    os.makedirs(root, exist_ok=True)
    shutil.copy(os.path.join(src, "reference.fa"),
                os.path.join(root, "reference.fa"))
    shutil.copytree(os.path.join(src, "fastq_pass"),
                    os.path.join(root, "fastq_pass"))
    return {
        "reference_file": os.path.join(root, "reference.fa"),
        "fastq_pass_dir": os.path.join(root, "fastq_pass"),
        "minimal_length": 600,
        "min_reads_per_cluster": 4,
        "read_batch_size": 96,
        "polish_method": "poa",
        "delete_tmp_files": False,
        "compile_cache_dir": os.path.join(
            os.path.dirname(root), "jax_cache"),
    }


def scenario_sustained(args) -> dict:
    """N tenants through one warm daemon, real pipeline: the SLO numbers
    (p50/p99 wait, first-stage latency, reads/s, steady compiles, cold
    start) plus the ledger entry the load gate regresses against."""
    from ont_tcrconsensus_tpu.obs import history as obs_history
    from ont_tcrconsensus_tpu.pipeline.config import RunConfig
    from ont_tcrconsensus_tpu.pipeline.run import _read_counts_csv
    from ont_tcrconsensus_tpu.serve.daemon import Daemon

    report = base_report(args, "sustained")
    src, lib = _build_library(args)
    n_reads = len(lib.reads)
    tenants = [
        _stage_tenant(src, os.path.join(args.workdir, f"tenant{i}"))
        for i in range(args.tenants)
    ]
    state_dir = os.path.join(args.workdir, "state")
    daemon = Daemon(dict(tenants[0]), port=0, state_dir=state_dir,
                    queue_max=max(args.queue_max, args.tenants),
                    prewarm_widths=[1024])
    th, out = _start_daemon_thread(daemon)
    srv = _wait_live_server()
    jobs_url = f"http://127.0.0.1:{srv.port}/jobs"
    ledger = Ledger()
    try:
        for raw in tenants:  # open-loop up-front burst: the queue absorbs
            st, body = _post(jobs_url, raw)
            ledger.record("ok", st, body)
        snaps = wait_terminal(jobs_url, list(ledger.accepted_ids),
                              args.timeout_s)
    finally:
        daemon.request_stop()
        th.join(timeout=300.0)
    done = [s for s in snaps.values() if s["state"] == "done"]
    counts = _terminal_counts(list(snaps.values()))
    started = [s["started_t"] for s in done if s.get("started_t")]
    finished = [s["finished_t"] for s in done if s.get("finished_t")]
    busy_s = (max(finished) - min(started)) if started and finished else None
    total_reads = n_reads * len(done)
    reads_per_sec = (round(total_reads / busy_s, 2)
                     if busy_s and busy_s > 0 else None)

    counts_exact = True
    for raw in tenants:
        path = os.path.join(raw["fastq_pass_dir"], "nano_tcr", "barcode01",
                            "counts", "umi_consensus_counts.csv")
        try:
            counts_exact &= _read_counts_csv(path) == lib.true_counts
        except OSError:
            counts_exact = False
    tele_path = os.path.join(tenants[-1]["fastq_pass_dir"], "nano_tcr",
                             "telemetry.json")
    steady_compiles = None
    try:
        with open(tele_path) as fh:
            steady_compiles = json.load(fh)["compile"]["count"]
    except (OSError, ValueError, KeyError):
        pass

    report.update({
        "submitted": ledger.submitted,
        "accepted": ledger.accepted,
        "rejected_by_reason": dict(sorted(ledger.rejected_by_reason.items())),
        "completed": counts["done"],
        "failed": counts["failed"],
        "poisoned": counts["poisoned"],
        "journaled_remaining": 0,
        "n_reads": total_reads,
        "reads_per_sec": reads_per_sec,
        "steady_compile_count": steady_compiles,
        "cold_start_s": daemon.warmup_s,
        "runner": "real",
    })
    report.update(summarize_waits(list(snaps.values())))
    report["drills"]["sustained"] = {
        "tenants": args.tenants, "reads_per_tenant": n_reads,
        "busy_window_s": round(busy_s, 3) if busy_s else None,
        "counts_exact": counts_exact, "exit_code": out["exit"],
        "prewarm": daemon.prewarm_report,
    }
    if not counts_exact:
        report["invariants"].append(
            "tenant counts CSVs do not match the simulator ground truth")
    if args.ledger:
        cfg = RunConfig.from_dict(dict(tenants[0]))
        entry = obs_history.build_entry(
            "serve_load",
            fingerprint=obs_history.config_fingerprint(cfg),
            sha=obs_history.git_sha(),
            backend=obs_history.detect_backend(),
            n_reads=total_reads,
            reads_per_sec=reads_per_sec,
            warmup_s=daemon.warmup_s,
            steady_s=busy_s,
            extra={
                "scenario": "sustained",
                "p50_wait_s": report["wait_s"]["p50"],
                "p99_wait_s": report["wait_s"]["p99"],
                "p50_first_stage_s": report["first_stage_s"]["p50"],
                "p99_first_stage_s": report["first_stage_s"]["p99"],
                "steady_compile_count": steady_compiles,
                "cold_start_s": daemon.warmup_s,
                "submitted": ledger.submitted,
                "accepted": ledger.accepted,
                "completed": counts["done"],
                "poisoned": counts["poisoned"],
                "rejected_by_reason": dict(ledger.rejected_by_reason),
            },
        )
        obs_history.append_entry(args.ledger, entry)
        report["drills"]["ledger_entry"] = {
            "path": args.ledger, "fingerprint": entry["fingerprint"]}
    return report


# --- scenarios: drain / crash (subprocess daemon) -----------------------------


def _spawn_daemon(template_path: str, state_dir: str, log_path: str,
                  env_extra: dict | None = None,
                  prewarm: bool = False) -> subprocess.Popen:
    env = dict(os.environ)
    env.update(env_extra or {})
    log = open(log_path, "ab")
    cmd = [sys.executable, "-m", "ont_tcrconsensus_tpu.pipeline.cli",
           "serve", template_path, "--cpu", "--port", "0",
           "--state-dir", state_dir]
    if not prewarm:
        cmd.append("--no-prewarm")
    return subprocess.Popen(cmd, env=env, stdout=log, stderr=log)


def _wait_serve_info(state_dir: str, pid: int,
                     timeout_s: float = 300.0) -> int:
    path = os.path.join(state_dir, "serve_info.json")
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            with open(path) as fh:
                info = json.load(fh)
            if info.get("pid") == pid:
                return int(info["port"])
        except (OSError, ValueError):
            pass
        time.sleep(0.1)
    raise RuntimeError(f"daemon (pid {pid}) never wrote {path}")


def _artifact_bytes(fastq_pass_dir: str) -> dict[str, bytes]:
    nano = os.path.join(fastq_pass_dir, "nano_tcr")
    out = {}
    for rel in (("barcode01", "counts", "umi_consensus_counts.csv"),
                ("barcode01", "fasta", "merged_consensus.fasta")):
        with open(os.path.join(nano, *rel), "rb") as fh:
            out["/".join(rel)] = fh.read()
    return out


def _subprocess_disruption(args, scenario: str) -> dict:
    """Shared drain/crash harness: uninterrupted baseline run, disrupted
    daemon generation 1, clean restart generation 2, byte-identity."""
    from ont_tcrconsensus_tpu.pipeline.config import RunConfig
    from ont_tcrconsensus_tpu.pipeline.run import run_with_config
    from ont_tcrconsensus_tpu.serve import queue as queue_mod

    report = base_report(args, scenario)
    src, lib = _build_library(args)
    # uninterrupted baseline in-process (same config the tenants get)
    baseline_root = os.path.join(args.workdir, "baseline")
    baseline_raw = _stage_tenant(src, baseline_root)
    run_with_config(RunConfig.from_dict(dict(baseline_raw)))
    want = _artifact_bytes(baseline_raw["fastq_pass_dir"])

    tenants = [
        _stage_tenant(src, os.path.join(args.workdir, f"tenant{i}"))
        for i in range(args.tenants)
    ]
    state_dir = os.path.join(args.workdir, "state")
    template_path = os.path.join(args.workdir, "template.json")
    with open(template_path, "w") as fh:
        json.dump(tenants[0], fh)
    log_path = os.path.join(args.workdir, "daemon.log")

    env_extra = {}
    if scenario == "crash":
        env_extra["TCR_CHAOS"] = json.dumps({
            "seed": args.seed,
            "faults": [{"site": "serve.daemon_loop", "kind": "error",
                        "message": "induced serve-loop crash"}],
        })
    # generation 1 prewarms: submissions land while the AOT prewarm still
    # holds the accept loop, so the disruption hits with EVERY job queued
    # (mid-load by construction, not by racing the loop)
    proc = _spawn_daemon(template_path, state_dir, log_path, env_extra,
                         prewarm=True)
    ledger = Ledger()
    accepted_tenants: list[dict] = []
    try:
        port = _wait_serve_info(state_dir, proc.pid)
        jobs_url = f"http://127.0.0.1:{port}/jobs"
        for raw in tenants:
            # a crash drill can kill the daemon between submits — a
            # refused connection is a LEDGERED outcome, not a harness
            # error (the accounting invariant must stay exact)
            try:
                st, body = _post(jobs_url, raw)
            except (urllib.error.URLError, ConnectionError, OSError):
                st, body = 0, {"error": "connection_refused"}
            ledger.record("ok", st, body)
            if st == 202:
                accepted_tenants.append(raw)
        if scenario == "drain":
            # pull the plug only once a job is actually IN FLIGHT, so the
            # drain exercises the stage-boundary handoff, not an idle stop
            deadline = time.monotonic() + args.timeout_s
            while time.monotonic() < deadline:
                st, listing = _get(jobs_url)
                if st == 200 and any(j.get("state") == "running"
                                     for j in listing.get("jobs", [])):
                    break
                time.sleep(0.2)
            time.sleep(args.drain_after_s)
            proc.send_signal(signal.SIGTERM)
            # mid-drain arrivals must get a machine-readable 503. Signal
            # delivery is asynchronous (the handler waits for the main
            # thread's next bytecode boundary), so probe until the drain
            # window is visible. The probe payload is OVER BUDGET on
            # purpose: before the flag lands it bounces as a cheap 409
            # (never queued, still ledgered); the draining check precedes
            # admission, so the first probe inside the window gets 503.
            report["drills"]["mid_drain_503"] = 0
            probe, _ = payload_for("over_budget", tenants[0])
            probe_deadline = time.monotonic() + 60.0
            while time.monotonic() < probe_deadline:
                try:
                    st, body = _post(jobs_url, probe)
                except (urllib.error.URLError, ConnectionError, OSError):
                    report["drills"]["mid_drain_503"] = "daemon_already_down"
                    break
                ledger.record("over_budget", st, body)
                if st == 503:
                    report["drills"]["mid_drain_503"] = 1
                    break
                time.sleep(0.2)
        rc = proc.wait(timeout=args.timeout_s)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=60.0)
    report["drills"]["disruption"] = {"exit_code": rc}
    if scenario == "drain" and rc != 143:
        report["invariants"].append(f"SIGTERM drain exited {rc}, want 143")
    if scenario == "crash" and rc == 0:
        report["invariants"].append("induced crash exited 0")

    # flight recorder flushed (crash-aware reason on the crash path)
    flight_path = os.path.join(state_dir, "logs", "flight_recorder.json")
    try:
        with open(flight_path) as fh:
            flight = json.load(fh)
        report["drills"]["flight_recorder"] = {
            "reason": flight.get("reason"), "events": len(
                flight.get("events", []))}
        if scenario == "crash" and not str(
                flight.get("reason", "")).startswith("serve_crash:"):
            report["invariants"].append(
                f"crash flush reason {flight.get('reason')!r} does not "
                "carry serve_crash:<Type>")
    except (OSError, ValueError):
        report["invariants"].append(
            f"flight recorder was not flushed to {flight_path}")

    journal_file = queue_mod.journal_path(state_dir)
    try:
        with open(journal_file) as fh:
            journaled = len(json.load(fh)["jobs"])
    except (OSError, ValueError):
        journaled = 0
        report["invariants"].append("no drain journal after disruption")
    gen1_done = ledger.accepted - journaled
    report["drills"]["journal"] = {"journaled": journaled}

    # generation 2: clean restart (no chaos), resume and complete
    proc2 = _spawn_daemon(template_path, state_dir, log_path)
    try:
        port2 = _wait_serve_info(state_dir, proc2.pid)
        jobs_url2 = f"http://127.0.0.1:{port2}/jobs"
        deadline = time.monotonic() + args.timeout_s
        listing: dict = {}
        while time.monotonic() < deadline:
            try:
                st, listing = _get(jobs_url2)
            except (urllib.error.URLError, ConnectionError, OSError):
                st, listing = 0, {}
            if st == 200 and listing.get("jobs_done", 0) >= journaled:
                break
            time.sleep(0.25)
        snaps = listing.get("jobs", [])
        proc2.send_signal(signal.SIGTERM)
        rc2 = proc2.wait(timeout=300.0)
    finally:
        if proc2.poll() is None:
            proc2.kill()
            proc2.wait(timeout=60.0)
    gen2_counts = _terminal_counts(snaps)
    report["drills"]["resume"] = {
        "resumed": len(snaps), "completed_after_restart":
        gen2_counts["done"], "exit_code": rc2,
        "journal_consumed": not os.path.exists(journal_file)}

    identical = True
    for raw in accepted_tenants:
        try:
            got = _artifact_bytes(raw["fastq_pass_dir"])
        except OSError:
            identical = False
            report["invariants"].append(
                f"missing output artifacts under {raw['fastq_pass_dir']}")
            continue
        for rel, blob in want.items():
            if got.get(rel) != blob:
                identical = False
                report["invariants"].append(
                    f"{raw['fastq_pass_dir']}: {rel} differs from the "
                    "uninterrupted baseline")
    report["drills"]["byte_identity"] = identical

    report.update({
        "submitted": ledger.submitted,
        "accepted": ledger.accepted,
        "rejected_by_reason": dict(sorted(ledger.rejected_by_reason.items())),
        "completed": gen1_done + gen2_counts["done"],
        "failed": gen2_counts["failed"],
        "poisoned": gen2_counts["poisoned"],
        "journaled_remaining": journaled - len(snaps),
        "runner": "real",
    })
    report.update(summarize_waits(snaps))
    return report


# --- CLI ----------------------------------------------------------------------


def parse_args(argv=None):
    ap = argparse.ArgumentParser(
        description="Seeded load + chaos harness for the warm-serving "
                    "daemon; emits a machine-readable load_report.json "
                    "with an exact rejection ledger.")
    ap.add_argument("--scenario", default="smoke",
                    choices=("smoke", "packed", "sustained", "drain",
                             "crash"))
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--mix",
                    default="ok=5,over_budget=2,invalid_config=2,"
                            "oversized_body=1",
                    help="seeded submission mix, e.g. 'ok=5,over_budget=1'")
    ap.add_argument("--period-s", type=float, default=1.5,
                    help="window the mix's offsets are drawn over")
    ap.add_argument("--queue-max", type=int, default=3)
    ap.add_argument("--burst", type=int, default=None,
                    help="saturation burst size (default queue_max + 2)")
    ap.add_argument("--runner", default=None, choices=("stub", "real"),
                    help="smoke only: 'stub' (default) replaces the "
                         "pipeline with a short sleep — control-plane "
                         "coverage in seconds")
    ap.add_argument("--stub-job-s", type=float, default=0.05)
    ap.add_argument("--workers", type=int, default=2,
                    help="packed scenario: runner-pool width (resident "
                         "jobs packed onto disjoint device slices)")
    ap.add_argument("--tenants", type=int, default=3)
    ap.add_argument("--regions", type=int, default=3)
    ap.add_argument("--molecules", type=int, default=2,
                    help="molecules per region (sustained dataset size)")
    ap.add_argument("--reads-per-molecule", type=int, default=5,
                    help="reads per molecule (scale this for big "
                         "sustained runs; counts stay exact)")
    ap.add_argument("--drain-after-s", type=float, default=5.0,
                    help="drain scenario: seconds of load before SIGTERM")
    ap.add_argument("--timeout-s", type=float, default=3600.0)
    ap.add_argument("--workdir", default=None,
                    help="scratch dir (default: a fresh temp dir)")
    ap.add_argument("--out", default="load_report.json")
    ap.add_argument("--ledger", default=None,
                    help="history ledger to append the source:serve_load "
                         "entry to (sustained scenario)")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend before importing the "
                         "pipeline (simulation environments)")
    args = ap.parse_args(argv)
    if args.runner is None:
        args.runner = ("stub" if args.scenario in ("smoke", "packed")
                       else "real")
    if args.runner == "stub" and args.scenario not in ("smoke", "packed"):
        ap.error("--runner stub is only meaningful for --scenario "
                 "smoke/packed")
    if args.runner == "real" and args.scenario == "packed":
        ap.error("--scenario packed is a control-plane drill; the real "
                 "data plane is covered by the slow packed e2e tests")
    if args.scenario == "packed" and args.workers < 2:
        ap.error("--scenario packed needs --workers >= 2")
    return args


def main(argv=None) -> int:
    args = parse_args(argv)
    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
    if args.workdir is None:
        import tempfile

        args.workdir = tempfile.mkdtemp(prefix="serve_load_")
    os.makedirs(args.workdir, exist_ok=True)

    if args.scenario == "packed" and "JAX_PLATFORMS" not in os.environ:
        # the pool needs >= workers devices; default to forced CPU
        # devices unless the caller picked a platform themselves
        os.environ["JAX_PLATFORMS"] = "cpu"
    if args.scenario == "packed":
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()

    runner = {
        "smoke": scenario_smoke,
        "packed": scenario_packed,
        "sustained": scenario_sustained,
        "drain": lambda a: _subprocess_disruption(a, "drain"),
        "crash": lambda a: _subprocess_disruption(a, "crash"),
    }[args.scenario]
    report = runner(args)

    report["invariants"] = (check_invariants(report)
                            + list(report.get("invariants", [])))
    schema_problems = validate_report(report)
    with open(args.out, "w") as fh:
        json.dump(report, fh, indent=1, sort_keys=True)
        fh.write("\n")
    print(f"serve_load: {args.scenario} report -> {args.out}",
          file=sys.stderr)
    print(json.dumps({k: report[k] for k in (
        "scenario", "submitted", "accepted", "completed", "poisoned",
        "failed", "rejected_by_reason", "wait_s", "reads_per_sec",
        "cold_start_s", "steady_compile_count")}, sort_keys=True))
    rc = 0
    for problem in report["invariants"]:
        print(f"serve_load: INVARIANT VIOLATED: {problem}", file=sys.stderr)
        rc = 1
    for problem in schema_problems:
        print(f"serve_load: REPORT SCHEMA: {problem}", file=sys.stderr)
        rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
