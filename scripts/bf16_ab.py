"""bf16 polisher serving exactness A/B (the gate artifact generator).

The polish fast path serves the bi-GRU in bfloat16 — ~2x MXU rate on TPU —
but ONLY behind an on-backend exactness gate: serving flips to bf16 when
(and only when) this A/B shows byte-identical consensus output on the
backend class the pipeline will run on. This script runs the A/B (fp32 vs
bf16 full pipeline polisher — shared vote consensus and pileup, so any
divergence is exactly a bf16-flipped polisher decision — over simulated
ONT-error clusters at depths 2/4/6/10) and writes the per-backend artifact
``models/weights/polisher_bf16_ab_<backend>.json`` that
``polisher.bf16_serving_certified`` consults.

Run it on the backend you will serve on (a retrain or weights-generation
change invalidates the artifact — the gate checks the weights basename):

    python scripts/bf16_ab.py                  # current backend
    python scripts/bf16_ab.py --force-cpu      # machinery check on host
    python scripts/bf16_ab.py --n 256          # deeper certification

Exit code 0 when identical (artifact certifies bf16), 1 when not (artifact
records the mismatch and serving stays fp32 — the gate's default).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n", type=int, default=96, help="clusters to A/B")
    ap.add_argument("--template-len", type=int, default=1300)
    ap.add_argument("--seed", type=int, default=17)
    ap.add_argument("--out", default=None,
                    help="artifact path (default: per-backend gate path)")
    ap.add_argument("--force-cpu", action="store_true")
    args = ap.parse_args(argv)

    import jax

    if args.force_cpu:
        # the axon plugin overrides JAX_PLATFORMS; the config API is the
        # only reliable CPU override (tests/conftest.py has the story)
        jax.config.update("jax_platforms", "cpu")

    from ont_tcrconsensus_tpu.models import polisher

    rec = polisher.run_bf16_exactness_ab(
        n_clusters=args.n, template_len=args.template_len, seed=args.seed,
        out_path=args.out,
    )
    print(json.dumps(rec, indent=1))
    if rec["identical"]:
        print(f"bf16_ab: IDENTICAL on {rec['backend']} — bf16 serving "
              "certified", file=sys.stderr)
        return 0
    print(f"bf16_ab: {rec['mismatched_clusters']}/{rec['n_clusters']} "
          f"clusters diverged on {rec['backend']} — serving stays fp32",
          file=sys.stderr)
    return 1


if __name__ == "__main__":
    raise SystemExit(main())
