"""Lane-scale proof run (VERDICT r2 next #5; north-star config #2 shape).

Runs >=N reads (default 1M-lane subsample shape: 100k on CPU, 1M on chip)
through the full two-round pipeline with a deliberately UMI-heavy region
(>=20k unique molecules in ONE region cluster) so the shortlist +
merge-repair clustering path (cluster/umi.py:164-272) runs in the regime
where shortlist misses and the O(U*K) pair stream matter. Emits a JSON
artifact with wall-time per stage, peak device memory, and counts-exactness
that the repo commits as LANE_SCALE.md.

Usage:
    python scripts/lane_scale_proof.py [--reads 100000] [--out LANE_SCALE.md]
                                       [--force-cpu]
"""

from __future__ import annotations

import argparse
import json
import os
import resource
import shutil
import sys
import time


def build_dataset(root: str, target_reads: int, seed: int = 47,
                  min_heavy: int = 20_000):
    """A library whose largest region cluster holds >=min_heavy unique UMIs
    (default 20k — the full lane proof; the medium regression tier passes
    a few hundred, still past the shortlist threshold of 256 uniques)."""
    from ont_tcrconsensus_tpu.io import fastx, simulator

    heavy_molecules = max(min_heavy, target_reads // 5)
    heavy_reads_per_mol = 3
    heavy_total = heavy_molecules * heavy_reads_per_mol
    rest = max(target_reads - heavy_total, 0)

    import numpy as np

    rng = np.random.default_rng(seed)
    ref = simulator.make_reference(rng, num_regions=24)
    names = list(ref)
    heavy_region = names[0]

    molecules = []
    for _ in range(heavy_molecules):
        molecules.append(simulator.Molecule(
            region=heavy_region,
            umi_fwd=simulator.instantiate_iupac(rng, "TTTVVTTVVVVTTVVVVTTVVVVTTVVVVTTT"),
            umi_rev=simulator.instantiate_iupac(rng, "AAABBBBAABBBBAABBBBAABBBBAABBAAA"),
            num_reads=heavy_reads_per_mol,
        ))
    # spread the rest over the other regions at depth 4
    other = names[1:]
    n_other_mols = rest // 4
    for i in range(n_other_mols):
        molecules.append(simulator.Molecule(
            region=other[i % len(other)],
            umi_fwd=simulator.instantiate_iupac(rng, "TTTVVTTVVVVTTVVVVTTVVVVTTVVVVTTT"),
            umi_rev=simulator.instantiate_iupac(rng, "AAABBBBAABBBBAABBBBAABBBBAABBAAA"),
            num_reads=4,
        ))

    err = simulator.OntErrorModel()
    reads = []
    for mi, mol in enumerate(molecules):
        template = (
            simulator.LEFT_FLANK + mol.umi_fwd + ref[mol.region]
            + mol.umi_rev + simulator.RIGHT_FLANK
        )
        template_rc = simulator.revcomp(template)
        for ri in range(mol.num_reads):
            orient = "-" if rng.random() < 0.5 else "+"
            seq, qual = simulator.mutate_ont(
                rng, template_rc if orient == "-" else template, err
            )
            reads.append((f"read_m{mi}_r{ri} mol={mi}", seq, qual))
    order = rng.permutation(len(reads))
    reads = [reads[i] for i in order]
    lib = simulator.SimulatedLibrary(reference=ref, molecules=molecules, reads=reads)

    os.makedirs(os.path.join(root, "fastq_pass", "barcode01"), exist_ok=True)
    fastx.write_fasta(os.path.join(root, "reference.fa"), ref.items())
    fastx.write_fastq(
        os.path.join(root, "fastq_pass", "barcode01", "barcode01.fastq.gz"),
        reads,
    )
    return lib, heavy_region, heavy_molecules


def peak_device_memory_gb() -> float | None:
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
        if stats and "peak_bytes_in_use" in stats:
            return stats["peak_bytes_in_use"] / 1e9
    except Exception:
        pass
    return None


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--reads", type=int, default=100_000)
    parser.add_argument("--out", default="LANE_SCALE.md")
    parser.add_argument("--root", default="/tmp/ont_tcr_lane_scale")
    parser.add_argument("--force-cpu", action="store_true")
    parser.add_argument("--min-heavy", type=int, default=20_000,
                        help="minimum unique molecules in the heavy region")
    parser.add_argument("--round2-full", action="store_true",
                        help="disable the targeted round-2 assign (A/B "
                             "comparison against the full fused pass)")
    args = parser.parse_args()

    if args.force_cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")

    from ont_tcrconsensus_tpu.pipeline.config import RunConfig
    from ont_tcrconsensus_tpu.pipeline.run import run_with_config

    root = args.root
    shutil.rmtree(root, ignore_errors=True)
    t0 = time.time()
    lib, heavy_region, heavy_molecules = build_dataset(
        root, args.reads, min_heavy=args.min_heavy
    )
    build_dt = time.time() - t0
    n_reads = len(lib.reads)
    print(f"dataset: {n_reads} reads, heavy region {heavy_region} with "
          f"{heavy_molecules} molecules; built in {build_dt:.0f}s", file=sys.stderr)

    cfg = RunConfig.from_dict({
        "reference_file": os.path.join(root, "reference.fa"),
        "fastq_pass_dir": os.path.join(root, "fastq_pass"),
        "minimal_length": 1000,
        "min_reads_per_cluster": 2,
        "delete_tmp_files": False,
        "write_intermediate_fastas": False,
        "error_profile_sample": 0,
        "round2_targeted_assign": not args.round2_full,
    })
    t1 = time.time()
    results = run_with_config(cfg)
    run_dt = time.time() - t1

    got = results.get("barcode01", {})
    want = lib.true_counts
    counts_exact = got == want
    diffs = {
        k: (got.get(k, 0), want.get(k, 0))
        for k in set(got) | set(want) if got.get(k, 0) != want.get(k, 0)
    }

    timing = {}
    logs_dir = os.path.join(root, "fastq_pass", "nano_tcr", "barcode01", "logs")
    tsv = os.path.join(logs_dir, "stage_timing.tsv")
    if os.path.exists(tsv):
        with open(tsv) as fh:
            next(fh)
            for line in fh:
                stage, sec, _ = line.split("\t")
                timing[stage] = round(float(sec), 1)

    # depth -> precision from the pipeline's OWN round-2 artifact (VERDICT
    # r4 #9): the depth-3 gate policy debate runs on this table, produced
    # by qc.analysis.estimate_precision_at_num_subreads (ref
    # minimap2_align.py:362-435) over merged_consensus QC rows.
    precision_at_depth = None
    sub_csv = os.path.join(
        logs_dir, "merged_consensus_number_of_subreads_blast_id.csv"
    )
    if os.path.exists(sub_csv):
        from ont_tcrconsensus_tpu.qc.analysis import (
            estimate_precision_at_num_subreads,
        )

        rows = []
        with open(sub_csv) as fh:
            next(fh)
            for line in fh:
                n, b = line.rstrip("\n").split(",")
                rows.append((n, float(b)))
        precision_at_depth = {
            str(k): v
            for k, v in estimate_precision_at_num_subreads(rows).items()
        }

    import jax

    artifact = {
        "n_reads": n_reads,
        "heavy_region_molecules": heavy_molecules,
        "round2_assign": "full" if args.round2_full else "targeted",
        "backend": jax.default_backend(),
        "wall_seconds": round(run_dt, 1),
        "reads_per_sec": round(n_reads / run_dt, 1),
        "counts_exact": counts_exact,
        "count_diffs": dict(list(diffs.items())[:20]),
        "heavy_region_count": (got.get(heavy_region, 0), heavy_molecules),
        "precision_at_depth": precision_at_depth,
        "stage_timing_sec": timing,
        "peak_device_mem_gb": peak_device_memory_gb(),
        "peak_host_rss_gb": round(
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6, 2
        ),
    }
    print(json.dumps(artifact, indent=2))
    with open(args.out, "w") as fh:
        fh.write("# Lane-scale proof (VERDICT r2 #5)\n\n")
        fh.write("Full two-round pipeline over a UMI-heavy library "
                 "(>=20k unique molecules in one region cluster, systematic "
                 "ONT error model):\n\n```json\n")
        fh.write(json.dumps(artifact, indent=2))
        fh.write("\n```\n")
    return 0 if counts_exact else 1


if __name__ == "__main__":
    raise SystemExit(main())
