"""Streaming-ingest proof: O(chunk) host memory at lane scale (VERDICT r3 #5).

Generates a ~1M-read FASTQ (the 70M-read real lane is ~100+ GB; 1M reads
~2 GB uncompressed is enough to separate O(file) from O(chunk) by an order
of magnitude), then drives the FULL ingest path — native C++ parse ->
bucketed padded batches — twice in fresh subprocesses:

  streamed:   io.native.parse_chunks -> bucketing.batch_parsed_chunks
              (the pipeline default since round 4)
  wholefile:  io.native.parse_file   -> bucketing.batch_parsed_reads
              (the pre-round-4 path, kept for references/tests)

and records each mode's peak RSS (ru_maxrss of the child). The proof is
that streamed peak RSS stays near the chunk size while whole-file RSS
scales with the file. Writes STREAMING_INGEST.md.

Run: python scripts/streaming_ingest_proof.py [--reads 1000000]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHILD = r"""
import json, resource, sys
sys.path.insert(0, __REPO__)
from ont_tcrconsensus_tpu.io import bucketing, native

mode, path = sys.argv[1], sys.argv[2]
if mode == "streamed":
    batches = bucketing.batch_parsed_chunks(
        native.parse_chunks(path), batch_size=1024
    )
else:
    batches = bucketing.batch_parsed_reads(
        native.parse_file(path), batch_size=1024
    )
n_batches = n_reads = total_bases = 0
for b in batches:
    n_batches += 1
    n_reads += int(b.valid.sum())
    total_bases += int(b.lengths.sum())
print(json.dumps({
    "n_batches": n_batches, "n_reads": n_reads, "total_bases": total_bases,
    "peak_rss_gb": round(
        resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6, 3
    ),
}))
"""


def build_fastq(path: str, n_reads: int, seed: int = 3) -> int:
    """Plain (uncompressed) FASTQ so RSS comparisons are about PARSING,
    not zlib buffers; ~2 kb reads like the assay."""
    import numpy as np

    rng = np.random.default_rng(seed)
    t0 = time.time()
    bases = np.frombuffer(b"ACGT", np.uint8)
    with open(path, "w") as fh:
        for i in range(n_reads):
            ln = int(rng.integers(1400, 2300))
            seq = bases[rng.integers(0, 4, ln)].tobytes().decode()
            qual = "I" * ln
            fh.write(f"@read{i} mol={i}\n{seq}\n+\n{qual}\n")
    size = os.path.getsize(path)
    print(f"built {n_reads} reads, {size/1e9:.2f} GB in {time.time()-t0:.0f}s",
          file=sys.stderr)
    return size


def run_mode(mode: str, path: str) -> dict:
    proc = subprocess.run(
        [sys.executable, "-c", CHILD.replace("__REPO__", repr(REPO)), mode, path],
        capture_output=True, text=True, timeout=3600,
    )
    if proc.returncode != 0:
        raise RuntimeError(f"{mode} failed: {proc.stderr[-500:]}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--reads", type=int, default=1_000_000)
    ap.add_argument("--root", default="/tmp/ont_tcr_stream_proof")
    ap.add_argument("--out", default=os.path.join(REPO, "STREAMING_INGEST.md"))
    ap.add_argument("--skip-wholefile", action="store_true")
    args = ap.parse_args()

    sys.path.insert(0, REPO)
    from ont_tcrconsensus_tpu.io import native

    if not native.available():
        print("native parser unavailable (no g++/zlib?) — nothing to prove",
              file=sys.stderr)
        return 2

    os.makedirs(args.root, exist_ok=True)
    path = os.path.join(args.root, "lane.fastq")
    size = build_fastq(path, args.reads)

    results = {}
    t0 = time.time()
    results["streamed"] = run_mode("streamed", path)
    results["streamed"]["wall_s"] = round(time.time() - t0, 1)
    if not args.skip_wholefile:
        t0 = time.time()
        results["wholefile"] = run_mode("wholefile", path)
        results["wholefile"]["wall_s"] = round(time.time() - t0, 1)

    for mode, r in results.items():
        print(f"{mode}: {r}", file=sys.stderr)
    s = results["streamed"]
    assert s["n_reads"] == args.reads, (s["n_reads"], args.reads)
    if "wholefile" in results:
        w = results["wholefile"]
        assert (s["n_batches"], s["n_reads"], s["total_bases"]) == (
            w["n_batches"], w["n_reads"], w["total_bases"]
        ), "streamed and whole-file ingest disagree"

    with open(args.out, "w") as fh:
        fh.write("# Streaming ingest proof (VERDICT r3 #5)\n\n")
        fh.write(
            f"{args.reads} reads, {size/1e9:.2f} GB plain FASTQ, full ingest "
            "path (native C++ parse -> bucketed padded batches), each mode "
            "in a fresh subprocess; peak RSS = ru_maxrss.\n\n"
        )
        fh.write("| mode | peak RSS (GB) | wall (s) | batches | reads |\n")
        fh.write("|---|---|---|---|---|\n")
        for mode, r in results.items():
            fh.write(
                f"| {mode} | {r['peak_rss_gb']} | {r['wall_s']} | "
                f"{r['n_batches']} | {r['n_reads']} |\n"
            )
        if "wholefile" in results:
            ratio = results["wholefile"]["peak_rss_gb"] / max(
                results["streamed"]["peak_rss_gb"], 1e-9
            )
            fh.write(
                f"\nWhole-file ingest peaks at {ratio:.1f}x the streamed "
                "path's RSS; the streamed path is the pipeline default for "
                "file sources (pipeline/assign.py _batches_from_source), so "
                "peak host memory is O(chunk + pending batches), independent "
                "of lane size (SURVEY §7 hard-part 5: a 70M-read lane is "
                "~100+ GB).\nBatch streams verified identical (count, reads, "
                "bases) between both modes.\n"
            )
    os.remove(path)
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
