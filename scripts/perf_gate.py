#!/usr/bin/env python
"""Noise-aware perf regression gate over the run-history ledger.

Compares one run (by default the ledger's newest entry) against the
baseline pool of earlier entries that agree with it on config
fingerprint, backend and n_reads (obs/history.py). The verdict is
median + MAD based: a run regresses only when its metric is worse than
the baseline median by more than

    max(--threshold * median, --mad-k * 1.4826 * MAD)

so a quiet baseline gates at the relative threshold while a noisy one
widens to what its own scatter justifies. Fewer than ``--min-samples``
matching baselines -> WARN and exit 0 (a thin ledger on a fresh machine
records instead of failing; see README "Cross-run observability").

Metric: ``reads_per_sec`` (higher is better; bench entries) when the
current entry carries one, else ``duration_s`` (lower is better; run
entries). A second, independent verdict gates the data plane:
``host_round_trip_bytes`` (lower is better; obs/transfers.py ledger),
so a PR that reintroduces a host round-trip fails with measured vs
allowed bytes. Entries predating the transfer ledger simply lack the
field — they are skipped for the byte pool (WARN when it goes thin,
never a crash) while remaining full baselines for the timing gate.

A third additive verdict gates the serving SLOs over the ledger's
``source:"serve_load"`` entries (scripts/serve_load.py reports): p99
job wait (lower better) and sustained reads_per_sec (higher better),
same allowance arithmetic. Ledgers with no load history WARN — the
load gate arms once a load report has been recorded.

Usage:
    python scripts/perf_gate.py LEDGER.jsonl [--current latest|entry.json]
        [--threshold 0.15] [--mad-k 4.0] [--min-samples 3] [--json]

Exit codes: 0 pass/warn, 1 regression, 2 usage / unreadable ledger.
Garbage ledger lines are skipped with a named stderr warning (never a
traceback) — the gate must stay usable on the artifact someone tore.
Never imports jax. Wired into scripts/tier1.sh as a smoke stage and
callable from ``bench.py --gate``.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ont_tcrconsensus_tpu.obs import history  # noqa: E402


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="Gate a run's perf against the run-history ledger "
        "(median + MAD over matching fingerprint/backend/n_reads entries)."
    )
    ap.add_argument("ledger", help="history .jsonl ledger path")
    ap.add_argument(
        "--current", default="latest",
        help="'latest' (default: the ledger's newest entry, gated against "
        "the rest) or a path to a JSON file holding one entry",
    )
    ap.add_argument("--threshold", type=float, default=0.15,
                    help="relative regression threshold vs the baseline "
                    "median (default 0.15 = 15%%)")
    ap.add_argument("--mad-k", type=float, default=4.0,
                    help="noise widening: allowance is at least this many "
                    "scaled MADs (default 4.0)")
    ap.add_argument("--min-samples", type=int, default=3,
                    help="matching baseline entries required to gate; "
                    "fewer -> WARN, exit 0 (default 3)")
    ap.add_argument("--json", action="store_true",
                    help="emit the verdict as one JSON line")
    ap.add_argument("--rt-budget", type=float, default=None, metavar="BYTES",
                    help="absolute host_round_trip_bytes ceiling for the "
                    "transfer verdict (the production data plane is "
                    "device-resident, so ~0 is the honest budget); gates "
                    "deterministically with no ledger history — omit to "
                    "use the relative median+MAD baseline gate")
    args = ap.parse_args(argv)

    entries, problems = history.read_entries(args.ledger)
    for p in problems:
        print(f"perf_gate: ledger {p}", file=sys.stderr)
    if not entries:
        print(f"perf_gate: no readable entries in {args.ledger}",
              file=sys.stderr)
        return 2
    if args.current == "latest":
        current = entries[-1]
    else:
        try:
            with open(args.current) as fh:
                current = json.load(fh)
        except (OSError, ValueError) as exc:
            print(f"perf_gate: unreadable --current {args.current!r}: "
                  f"{exc!r}", file=sys.stderr)
            return 2
        if not isinstance(current, dict):
            print(f"perf_gate: --current {args.current!r} is not a JSON "
                  "object", file=sys.stderr)
            return 2

    result = history.evaluate_gate(
        entries, current, rel_threshold=args.threshold,
        mad_k=args.mad_k, min_samples=args.min_samples,
    )
    transfer = history.evaluate_bytes_gate(
        entries, current, rel_threshold=args.threshold,
        mad_k=args.mad_k, min_samples=args.min_samples,
        abs_budget=args.rt_budget,
    )
    # serving-SLO verdict: gate the current entry when it IS a load
    # report, else the ledger's newest serve_load entry (warn when none)
    load = history.evaluate_load_gate(
        entries,
        current if current.get("source") == "serve_load" else None,
        rel_threshold=args.threshold, mad_k=args.mad_k,
        min_samples=args.min_samples,
    )
    if args.json:
        # one JSON object on stdout (consumers json.loads the whole
        # stream); the transfer + load verdicts ride additive keys
        body = dataclasses.asdict(result)
        body["transfer"] = dataclasses.asdict(transfer)
        body["load"] = dataclasses.asdict(load)
        print(json.dumps(body, sort_keys=True))
    else:
        print(f"perf_gate: {result.status.upper()} — {result.reason}")
        print(f"perf_gate: transfer {transfer.status.upper()} — "
              f"{transfer.reason}")
        print(f"perf_gate: load {load.status.upper()} — {load.reason}")
    return 1 if "fail" in (result.status, transfer.status,
                           load.status) else 0


if __name__ == "__main__":
    sys.exit(main())
