"""Depth-2 residual error composition probe (VERDICT r4 #2 follow-through).

LANE_SCALE_R5.md leaves 121/8000 heavy-region molecules uncounted, all in
the depth-2 chain: clusters attrited to exactly 2 effective reads whose
polished consensus still fails the round-2 blast-id > 0.99 bar. Before
building anything, this probe measures WHAT the surviving errors are, on
the same simulator regime the lane proof uses:

- per-cluster error count vs the ~1%-of-length budget the bar implies;
- per-error class (sub / del / ins, from the cs-tag vs truth);
- homopolymer context (inside or adjacent to a truth run >= 3);
- subread evidence at the error column (pileup base_at): did the two
  reads AGREE on the wrong base (correlated error — only a learned prior
  can fix it) or DISAGREE (arbitration loss — a better tie-break rule or
  richer features can fix it)?

The split drives the next move: majority-disagreement -> engineer the
depth-2 merge; majority-correlated -> train for the prior (or accept the
bound and document it, as medaka-at-depth-2 accepts its own).

Run (CPU fine, ~150 clusters):
    python scripts/depth2_probe.py [--n 150] [--out DEPTH2_PROBE.json]
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from ont_tcrconsensus_tpu.io import simulator  # noqa: E402
from ont_tcrconsensus_tpu.models import polisher, train  # noqa: E402
from ont_tcrconsensus_tpu.ops import consensus, encode  # noqa: E402
from ont_tcrconsensus_tpu.qc.error_profile import banded_cs  # noqa: E402

BLAST_BAR = 0.99


def hp_mask(truth: np.ndarray, min_run: int = 3) -> np.ndarray:
    """True where the truth base sits inside (or borders) a run >= min_run."""
    n = truth.size
    mask = np.zeros(n, bool)
    i = 0
    while i < n:
        j = i
        while j < n and truth[j] == truth[i]:
            j += 1
        if j - i >= min_run:
            mask[max(i - 1, 0): min(j + 1, n)] = True
        i = j
    return mask


def parse_cs(cs: str):
    """Yield (op, ref_pos, length) per difference; ops: sub/del/ins.

    ref_pos is the truth coordinate where the difference applies (for an
    insertion: the truth position it precedes).
    """
    pos = 0
    for m in re.finditer(r":(\d+)|\*([a-z])([a-z])|\+([a-z]+)|-([a-z]+)", cs):
        if m.group(1):
            pos += int(m.group(1))
        elif m.group(2):
            yield ("sub", pos, 1)
            pos += 1
        elif m.group(4):
            yield ("ins", pos, len(m.group(4)))
        else:
            yield ("del", pos, len(m.group(5)))
            pos += len(m.group(5))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=150)
    ap.add_argument("--template-len", type=int, default=1300)
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--out", default=os.path.join(REPO, "DEPTH2_PROBE.json"))
    ap.add_argument("--batch", type=int, default=16)
    args = ap.parse_args(argv)

    import jax.numpy as jnp

    rng = np.random.default_rng(args.seed)
    err = (0.01, 0.004, 0.004)
    model = train.DEFAULT_ERROR_MODEL
    width = train._auto_width(args.template_len)

    main_params = polisher.load_params(polisher.serving_weights_path())
    low_params = polisher.load_low_depth_params()
    polish = polisher.make_pipeline_polisher(
        main_params, min_polish_depth=4,
        low_depth_params=low_params, low_depth=2,
    )

    agg = {
        "n_clusters": 0, "pass_vote": 0, "pass_polish": 0,
        "errors_vote": [], "errors_polish": [],
        "by_class": {"sub": 0, "del": 0, "ins": 0},
        "by_hp": {"hp": 0, "non_hp": 0},
        "by_evidence": {"agreed_wrong": 0, "disagreed": 0, "uncovered": 0},
    }

    done = 0
    while done < args.n:
        cb = min(args.batch, args.n - done)
        truths = []
        codes = np.full((cb, 2, width), encode.PAD_CODE, np.uint8)
        lens = np.zeros((cb, 2), np.int32)
        quals = np.zeros((cb, 2, width), np.uint8)
        strands = np.zeros((cb, 2), bool)
        for c in range(cb):
            template = simulator._rand_seq(rng, args.template_len)
            template_rc = simulator.revcomp(template)
            truths.append(encode.encode_seq(template))
            for i in range(2):
                r, q, is_rev = train._simulate_oriented_read(
                    rng, template, template_rc, err, model
                )
                codes[c, i, : len(r)] = r
                quals[c, i, : len(q)] = q
                lens[c, i] = len(r)
                strands[c, i] = is_rev
        drafts, dlens = consensus.consensus_clusters_batch(
            codes, lens, rounds=4, band_width=consensus.POLISH_BAND_WIDTH
        )
        drafts, dlens = np.asarray(drafts), np.asarray(dlens)
        pol, plens = polish(codes, lens, drafts, dlens,
                            quals=quals, strands=strands)

        # evidence: per-subread base at each POLISHED-draft column
        from ont_tcrconsensus_tpu.ops import pileup as pileup_mod
        ba, _, _, _, _ = pileup_mod.pileup_columns_batch(
            jnp.asarray(codes), jnp.asarray(lens), jnp.asarray(pol),
            jnp.asarray(plens), band_width=consensus.POLISH_BAND_WIDTH,
            out_len=pol.shape[1],
        )
        ba = np.asarray(ba)  # (C, 2, W) base code per subread per column

        for c in range(cb):
            truth = truths[c]
            v = drafts[c, : dlens[c]]
            p = pol[c, : plens[c]]
            cs_v = banded_cs(v, truth)
            cs_p = banded_cs(p, truth)
            ev = sum(l for _, _, l in parse_cs(cs_v))
            ep = sum(l for _, _, l in parse_cs(cs_p))
            cols_v = max(len(truth), len(v))
            cols_p = max(len(truth), len(p))
            agg["errors_vote"].append(int(ev))
            agg["errors_polish"].append(int(ep))
            agg["pass_vote"] += (1 - ev / cols_v) > BLAST_BAR
            agg["pass_polish"] += (1 - ep / cols_p) > BLAST_BAR
            hp = hp_mask(truth)
            # map truth pos -> polished-draft col: walk the cs ops
            # (approximate for classification: use truth pos scaled; exact
            # mapping derived from the cs walk below)
            tpos_to_ppos = np.full(len(truth) + 1, -1, np.int64)
            t = q = 0
            for mm in re.finditer(
                r":(\d+)|\*([a-z])([a-z])|\+([a-z]+)|-([a-z]+)", cs_p
            ):
                if mm.group(1):
                    k = int(mm.group(1))
                    tpos_to_ppos[t: t + k] = np.arange(q, q + k)
                    t += k
                    q += k
                elif mm.group(2):
                    tpos_to_ppos[t] = q
                    t += 1
                    q += 1
                elif mm.group(4):
                    q += len(mm.group(4))
                else:
                    k = len(mm.group(5))
                    # deletion-consumed truth positions map to the FLANKING
                    # polished column (the q position the deletion applies
                    # before) so their pileup evidence is inspectable;
                    # leaving them -1 misbucketed every deletion error as
                    # 'uncovered' (DEPTH2_PROBE.json: uncovered==del==559),
                    # silently excluding ~22% of errors from the
                    # agreed/disagreed split VERDICT decisions rest on
                    tpos_to_ppos[t : t + k] = q
                    t += k
            for op, tp, ln in parse_cs(cs_p):
                agg["by_class"][op] += 1
                in_hp = bool(hp[min(tp, len(truth) - 1)])
                agg["by_hp"]["hp" if in_hp else "non_hp"] += 1
                pp = tpos_to_ppos[min(tp, len(truth))]
                if op == "del" and pp >= plens[c]:
                    pp = plens[c] - 1  # deletion at the draft end flanks left
                if pp < 0 or pp >= plens[c]:
                    agg["by_evidence"]["uncovered"] += 1
                    continue
                b0, b1 = ba[c, 0, pp], ba[c, 1, pp]
                if b0 == b1:
                    agg["by_evidence"]["agreed_wrong"] += 1
                else:
                    agg["by_evidence"]["disagreed"] += 1
        agg["n_clusters"] += cb
        done += cb
        print(f"depth2_probe: {done}/{args.n} "
              f"pass_polish={agg['pass_polish']}/{done}", file=sys.stderr)

    ev = np.array(agg["errors_vote"])
    ep = np.array(agg["errors_polish"])
    budget = int(args.template_len * (1 - BLAST_BAR))
    result = {
        "n_clusters": agg["n_clusters"],
        "template_len": args.template_len,
        "error_budget_per_cluster": budget,
        "pass_rate_vote": agg["pass_vote"] / agg["n_clusters"],
        "pass_rate_polish": agg["pass_polish"] / agg["n_clusters"],
        "errors_per_cluster_vote": {
            "mean": float(ev.mean()), "p50": float(np.median(ev)),
            "p90": float(np.percentile(ev, 90)),
        },
        "errors_per_cluster_polish": {
            "mean": float(ep.mean()), "p50": float(np.median(ep)),
            "p90": float(np.percentile(ep, 90)),
        },
        "by_class": agg["by_class"],
        "by_hp": agg["by_hp"],
        "by_evidence": agg["by_evidence"],
    }
    with open(args.out, "w") as fh:
        json.dump(result, fh, indent=1)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
