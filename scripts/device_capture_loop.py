"""Session-long opportunistic TPU capture loop.

The axon TPU tunnel flaps for hours at a time (rounds 2-3 ended with zero
device evidence because every capture attempt happened to land in an
outage).  This loop converts ANY window of tunnel uptime into committed
perf artifacts:

  1. probes the backend every ``--interval`` seconds in a timeout-wrapped
     subprocess (jax.devices() hangs indefinitely when the tunnel is
     wedged, so the probe must be killable);
  2. logs every probe to TUNNEL_LOG.md — the outage record itself is a
     deliverable (proof the loop ran all session);
  3. on the first success runs, in order of cost:
       a. kernel_bench.py            -> KERNEL_BENCH.json   (<60 s warm)
       b. bench.py BENCH_READS=2000  -> BENCH_TPU_CAPTURE.json
       c. bench.py (full 10k reads)  -> BENCH_TPU_CAPTURE_FULL.json
     Each step is independently resumable: partial kernel results survive
     (kernel_bench writes after every kernel), and the persistent compile
     cache makes a post-outage retry skip straight to execution.

Run it in the background for the whole session:
    python scripts/device_capture_loop.py &
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from bench import probe_once  # noqa: E402  (shared single-attempt probe)

LOG = os.path.join(REPO, "TUNNEL_LOG.md")
KERNEL_OUT = os.path.join(REPO, "KERNEL_BENCH.json")
BENCH_OUT = os.path.join(REPO, "BENCH_TPU_CAPTURE.json")
BENCH_FULL_OUT = os.path.join(REPO, "BENCH_TPU_CAPTURE_FULL.json")
TPU_LANE_LOG = os.path.join(REPO, "TPU_LANE_PASS.log")
BF16_AB_OUT = os.path.join(
    REPO, "models", "weights", "polisher_bf16_ab_tpu.json")


def bf16_ab_done() -> bool:
    """A committed on-chip bf16 A/B artifact (scripts/bf16_ab.py): the
    per-backend record the serving path consults before enabling bf16."""
    try:
        with open(BF16_AB_OUT) as fh:
            rec = json.load(fh)
        return rec.get("backend") == "tpu" and "identical" in rec
    except (OSError, json.JSONDecodeError):
        return False


def pileup_cert_done() -> bool:
    """The lane-packed pileup kernel's certification verdict is committed:
    KERNEL_BENCH.json carries lane_packed_certified (either verdict — the
    committed answer is the deliverable, kernel_bench states the target)."""
    try:
        with open(KERNEL_OUT) as fh:
            rep = json.load(fh)
        k = rep.get("kernels", {}).get("pileup", {})
        return (rep.get("platform") == "tpu"
                and k.get("value") is not None
                and isinstance(k.get("lane_packed_certified"), bool))
    except (OSError, json.JSONDecodeError):
        return False


def tpu_lane_done() -> bool:
    """A committed log proving the CURRENT head's Pallas kernels passed on
    a real chip: pytest summary must show passes and no skips (the lane
    tests self-skip without a chip, which would be a vacuous artifact)."""
    try:
        with open(TPU_LANE_LOG) as fh:
            text = fh.read()
    except OSError:
        return False
    return " passed" in text and "skipped" not in text and "failed" not in text


def log_line(text: str) -> None:
    stamp = time.strftime("%Y-%m-%d %H:%M:%SZ", time.gmtime())
    new = not os.path.exists(LOG)
    with open(LOG, "a") as fh:
        if new:
            fh.write(
                "# TPU tunnel availability log\n\n"
                "Written by scripts/device_capture_loop.py — one line per "
                "backend probe / capture attempt, for the whole session.\n\n"
            )
        fh.write(f"- {stamp} {text}\n")
    print(f"capture_loop: {text}", file=sys.stderr, flush=True)


def kernel_done(*names: str) -> bool:
    names = names or ("sw", "pileup", "rnn", "fused", "fused_fast")
    try:
        with open(KERNEL_OUT) as fh:
            rep = json.load(fh)
        return rep.get("platform") == "tpu" and all(
            rep.get("kernels", {}).get(k, {}).get("value") is not None
            for k in names
        )
    except (OSError, json.JSONDecodeError):
        return False


def bench_done(path: str) -> bool:
    try:
        with open(path) as fh:
            line = json.load(fh)
        return (isinstance(line, dict)
                and float(line.get("value", 0.0)) > 0.0
                and "stale_capture" not in line
                and "error" not in line)
    except (OSError, ValueError):
        return False


def run_capture(cmd: list[str], timeout: float, out_path: str | None,
                env_extra: dict | None = None, label: str = "",
                verify=None, stderr_path: str | None = None) -> bool:
    env = dict(os.environ)
    env.update(env_extra or {})
    log_line(f"CAPTURE start: {label}")
    t0 = time.time()
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=timeout,
            cwd=REPO, env=env,
        )
    except subprocess.TimeoutExpired:
        log_line(f"CAPTURE timeout after {timeout:.0f}s: {label}")
        return False
    dt = time.time() - t0
    if stderr_path and proc.stderr:
        # the bench's stderr carries the per-stage timing table — the
        # on-chip BENCH_BREAKDOWN evidence VERDICT r3 #2 asks for
        with open(stderr_path, "w") as fh:
            fh.write(proc.stderr)
    tail = (proc.stderr or "").strip().splitlines()[-3:]
    if proc.returncode != 0:
        log_line(
            f"CAPTURE rc={proc.returncode} after {dt:.0f}s: {label} "
            f"({' | '.join(tail)})"
        )
        return False
    if out_path is not None and proc.stdout.strip():
        if out_path.endswith(".log"):
            with open(out_path, "w") as fh:
                fh.write(proc.stdout)
        else:
            last = proc.stdout.strip().splitlines()[-1]
            with open(out_path, "w") as fh:
                fh.write(last + "\n")
    # rc==0 is not success: bench.py deliberately exits 0 with an error
    # JSON line when its own probe fails — only the artifact check decides
    if verify is not None and not verify():
        log_line(
            f"CAPTURE rc=0 but artifact invalid after {dt:.0f}s: {label} "
            f"({' | '.join(tail[-1:])})"
        )
        return False
    log_line(f"CAPTURE ok after {dt:.0f}s: {label} ({' | '.join(tail[-1:])})")
    return True


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--interval", type=float, default=150.0,
                    help="probe period (s) while captures are pending")
    ap.add_argument("--idle-interval", type=float, default=600.0,
                    help="probe period (s) once every capture is done")
    args = ap.parse_args()

    log_line("loop started "
             f"(pid {os.getpid()}, interval {args.interval:.0f}s)")

    # capture stages, cheapest first. A deterministically failing stage must
    # not starve the others (review finding): the eligible stage with the
    # FEWEST attempts runs next, which round-robins across failing stages
    # while naturally preferring untried ones.
    stages = [
        {
            # tier 0 (VERDICT r4 #1): the cheapest possible on-chip artifact.
            # kernel_bench merges into KERNEL_OUT incrementally, so this
            # sw-only run and the full run below share one report file and
            # even 2 minutes of uptime yields a committed Gcell/s number.
            "label": "kernel_bench sw only", "attempts": 0,
            "done": lambda: kernel_done("sw"),
            "cmd": [sys.executable, "kernel_bench.py", "--kernel", "sw",
                    "--out", KERNEL_OUT],
            "timeout": 600, "out": None, "env": None,
        },
        {
            "label": "kernel_bench", "attempts": 0,
            "done": kernel_done,
            "cmd": [sys.executable, "kernel_bench.py", "--out", KERNEL_OUT],
            "timeout": 1800, "out": None, "env": None,
        },
        {
            # the lane-packed pileup certification verdict
            # (lane_packed_certified vs the 100 Gcell/s target) is absent
            # from pre-upgrade KERNEL_BENCH.json captures: re-run just the
            # pileup kernel to commit it without discarding older results
            "label": "kernel_bench pileup cert", "attempts": 0,
            "done": pileup_cert_done,
            "cmd": [sys.executable, "kernel_bench.py", "--kernel", "pileup",
                    "--out", KERNEL_OUT],
            "timeout": 900, "out": None, "env": None,
        },
        {
            # bf16 RNN serving settle: the per-backend exactness A/B
            # artifact models/polisher.py consults before enabling the
            # bf16 fast path. EITHER verdict is the deliverable (diverged
            # -> serving stays fp32, and the loop stops retrying).
            "label": "bf16_ab", "attempts": 0,
            "done": bf16_ab_done,
            "cmd": [sys.executable, "scripts/bf16_ab.py"],
            "timeout": 1800, "out": None, "env": None,
        },
        {
            # VERDICT r4 #8: tie the CURRENT head's Pallas kernels to a
            # real-chip pass (band-128 SW parity last ran on r3's head).
            "label": "tpu_lane pytest", "attempts": 0,
            "done": tpu_lane_done,
            "cmd": [sys.executable, "-m", "pytest",
                    "tests/test_tpu_lane.py", "-x", "-q", "-rs"],
            "timeout": 1800, "out": TPU_LANE_LOG, "env": None,
        },
        {
            "label": "bench 2k reads", "attempts": 0,
            "done": lambda: bench_done(BENCH_OUT),
            "cmd": [sys.executable, "bench.py"],
            "timeout": 3000, "out": BENCH_OUT,
            "env": {"BENCH_READS": "2000", "BENCH_NO_FALLBACK": "1"},
            "stderr": BENCH_OUT + ".stderr.log",
        },
        {
            "label": "bench 10k reads", "attempts": 0,
            "done": lambda: bench_done(BENCH_FULL_OUT),
            "cmd": [sys.executable, "bench.py"],
            "timeout": 5400, "out": BENCH_FULL_OUT,
            "env": {"BENCH_NO_FALLBACK": "1"},
            "stderr": BENCH_FULL_OUT + ".stderr.log",
        },
    ]

    consecutive_down = 0
    consecutive_up = 0
    while True:
        plat, detail = probe_once()
        if plat != "tpu":
            consecutive_up = 0
            consecutive_down += 1
            # one line per state change + a heartbeat every 10 probes, so
            # the log stays readable over a 12 h session
            if consecutive_down == 1 or consecutive_down % 10 == 0:
                log_line(
                    f"DOWN ({detail if plat is None else plat}, "
                    f"{consecutive_down} consecutive)"
                )
            time.sleep(args.interval)
            continue
        if consecutive_down:
            log_line(f"UP after {consecutive_down} down probes")
        elif consecutive_up == 0 or consecutive_up % 10 == 0:
            log_line(f"UP ({consecutive_up + 1} consecutive)")
        consecutive_down = 0
        consecutive_up += 1

        pending = [s for s in stages if not s["done"]()]
        if not pending:
            time.sleep(args.idle_interval)
            continue
        stage = min(pending, key=lambda s: s["attempts"])
        stage["attempts"] += 1
        run_capture(
            stage["cmd"], timeout=stage["timeout"], out_path=stage["out"],
            env_extra=stage["env"],
            label=f"{stage['label']} (attempt {stage['attempts']})",
            verify=stage["done"], stderr_path=stage.get("stderr"),
        )
        time.sleep(5)  # re-probe promptly between capture steps


if __name__ == "__main__":
    main()
